"""AST-based dygraph->static conversion (`@to_static` control flow).

Role parity: reference python/paddle/fluid/dygraph/dygraph_to_static/
(program_translator.py, ast_transformer.py, ifelse_transformer.py,
loop_transformer.py, break_continue_transformer.py, convert_operators.py)
— the 25-file transpiler collapsed to one module by the same two-phase
design the reference uses:

1. **Compile time**: the function's AST is rewritten once.  `if`/`while`/
   `for range(...)` over possibly-tensor values become calls into the
   `convert_*` runtime shims, with the branch/loop bodies extracted into
   local functions that take the written-to variables as arguments and
   return them (undefined-before-branch names are passed as a loud
   ``_UNDEF`` sentinel, the reference's UndefinedVar).  `break`/
   `continue` are rewritten into guard flags exactly like the
   reference's BreakContinueTransformer; `and`/`or`/`not` become lazy
   `convert_logical_*` calls that preserve python short-circuiting.

2. **Runtime**: each shim dispatches on the condition's actual type —
   plain python values take the ordinary python path (zero overhead for
   non-tensor control flow), static-graph `Variable`s build
   `layers.cond`/`layers.while_loop` ops, and dygraph Tensors under an
   active trace record real `cond_pair`/`while` ops with sub-blocks
   into the traced program, so `jit.save` exports data-dependent
   control flow instead of baking in one branch.
"""
from __future__ import annotations

import ast
import inspect
import textwrap
from typing import List

import numpy as np

from ..framework import unique_name


class _Undefined:
    """Loud placeholder for names not yet bound when a branch runs
    (reference UndefinedVar): any actual USE raises immediately."""

    def __init__(self, name):
        self._name = name

    def _die(self, *a, **k):
        raise NameError(
            f"variable {self._name!r} is used in a converted branch/loop "
            f"before being assigned on every path; give it a value before "
            f"the if/loop")

    __call__ = __add__ = __radd__ = __sub__ = __mul__ = __bool__ = _die
    __getattr__ = __getitem__ = __float__ = __int__ = _die

    def __repr__(self):
        return f"<undefined {self._name}>"


def _is_dytensor(x):
    from .tensor import Tensor

    return isinstance(x, Tensor)


def _is_static_var(x):
    from ..framework.program import Variable

    return isinstance(x, Variable)


def _truth(x):
    if isinstance(x, _Undefined):
        x._die()
    return bool(x)


def _tracing():
    from . import eager

    return eager._TRACE_REC


class _suspend_trace:
    def __enter__(self):
        from . import eager

        self._rec = eager._TRACE_REC
        eager._TRACE_REC = None

    def __exit__(self, *exc):
        from . import eager

        eager._TRACE_REC = self._rec
        return False


def _wrap_tensor(v, name="value"):
    from .tensor import Tensor

    if isinstance(v, _Undefined):
        v._die()
    if _is_dytensor(v):
        return v
    return Tensor(np.asarray(v))


def _flat(res):
    if isinstance(res, tuple):
        return list(res), True
    return [res], False


def _fresh_like(t):
    """New Tensor object over the same value (so binding it to a new var
    name leaves the source object's name untouched)."""
    from .tensor import Tensor

    nt = Tensor(t._value)
    nt.stop_gradient = getattr(t, "stop_gradient", True)
    return nt


# ---------------------------------------------------------------------------
# runtime shims
# ---------------------------------------------------------------------------

def convert_ifelse(pred, true_fn, false_fn, names, caller_locals,
                   returning=False):
    """Reference convert_operators.convert_ifelse."""
    args = tuple(caller_locals.get(n, _Undefined(n)) for n in names)
    if _is_static_var(pred):
        from .. import layers

        out = layers.cond(pred, lambda: true_fn(*args),
                          lambda: false_fn(*args))
        return out
    rec = _tracing()
    if rec is not None and _is_dytensor(pred):
        return _trace_ifelse(rec, pred, true_fn, false_fn, args)
    return true_fn(*args) if _truth(pred) else false_fn(*args)


def _trace_ifelse(rec, pred, true_fn, false_fn, args):
    pred_name = rec.ensure_name(pred)
    parent = rec.block

    def capture(fn):
        sub = rec.begin_sub_block()
        res = fn(*args)
        vals, is_tuple = _flat(res)
        ts = [_wrap_tensor(v) for v in vals]
        names = [rec.ensure_name(t) for t in ts]
        rec.end_sub_block(parent)
        return sub, res, ts, names, is_tuple

    sub_t, t_res, t_ts, t_names, t_tuple = capture(true_fn)
    sub_f, f_res, f_ts, f_names, f_tuple = capture(false_fn)
    if len(t_names) != len(f_names) or t_tuple != f_tuple:
        raise TypeError(
            f"converted if/else branches return different structures "
            f"({len(t_names)} vs {len(f_names)} values)")

    taken_ts = t_ts if _truth(pred) else f_ts
    out_names = []
    for t in taken_ts:
        name = rec.new_parent_var(parent, t)
        out_names.append(name)
    parent.append_op("cond_pair", {"Cond": [pred_name]},
                     {"Out": out_names},
                     {"sub_block_t": sub_t.idx, "sub_block_f": sub_f.idx,
                      "t_outs": t_names, "f_outs": f_names})
    # bind FRESH tensor objects to the cond outputs: a passthrough branch
    # returns the caller's own tensor, and re-pointing that object would
    # clobber the name every other reference to the original value uses
    outs = []
    for t, n in zip(taken_ts, out_names):
        nt = _fresh_like(t)
        rec.bind(nt, n)
        outs.append(nt)
    if t_tuple:
        return tuple(outs)
    return outs[0]


def ret_select(flag, then_fn, else_fn):
    """Value select for the rewritten return cascade
    (_ReturnRewriter): chooses the fired return site's expression.
    Python flags evaluate only the taken leg; tensor flags trace both
    and merge (layers.cond in static graphs, cond_pair under trace)."""
    if _is_static_var(flag):
        from .. import layers

        return layers.cond(flag, then_fn, else_fn)
    rec = _tracing()
    if rec is not None and _is_dytensor(flag):
        return _trace_ifelse(rec, flag, lambda: then_fn(),
                             lambda: else_fn(), ())
    return then_fn() if _truth(flag) else else_fn()


def convert_while_loop(cond_fn, body_fn, names, caller_locals):
    """Reference convert_operators.convert_while_loop."""
    args = tuple(caller_locals.get(n, _Undefined(n)) for n in names)
    probe = cond_fn(*args)
    if _is_static_var(probe):
        from .. import layers

        out = layers.while_loop(lambda *vs: cond_fn(*vs),
                                lambda *vs: list(body_fn(*vs)),
                                list(args))
        return tuple(out)
    rec = _tracing()
    if rec is not None and _is_dytensor(probe):
        return _trace_while(rec, cond_fn, body_fn, args, probe)
    # plain python — but under an active trace the condition can BECOME
    # a tensor mid-loop (a python-range loop whose break flag is data-
    # dependent): peel the already-run iterations and hand the rest to
    # the traced while op
    vals = args
    c = probe
    while True:
        if rec is not None and _is_dytensor(c):
            return _trace_while(rec, cond_fn, body_fn, tuple(vals), c)
        if not _truth(c):
            return vals
        vals = body_fn(*vals)
        c = cond_fn(*vals)


def _trace_while(rec, cond_fn, body_fn, args, probe=None):
    # python scalars join the carry as tensors (XLA loop state must be
    # arrays); UNDEF entering the carry dies only when actually used
    vals = tuple(
        v if isinstance(v, _Undefined) else _wrap_tensor(v) for v in args)
    parent = rec.block

    def carry_name(v):
        """A loop-carried var must be a per-call TEMPORARY: captured
        python scalars (break/return flags) land in persistable consts,
        and carrying the const itself would make the while's write-back
        mutate saved state — replay N's final flag would become replay
        N+1's initial value.  Copy persistables into a parent temp and
        carry that."""
        n = rec.ensure_name(v)
        var = parent._find_var_recursive(n)
        if var is not None and getattr(var, "persistable", False):
            tmp = rec.new_parent_var(parent, v)
            parent.append_op("assign", {"X": [n]}, {"Out": [tmp]}, {})
            rec.bind(v, tmp)
            return tmp
        return n

    var_names = [carry_name(v) if not isinstance(v, _Undefined)
                 else None for v in vals]

    if probe is not None and all(v is a for v, a in zip(vals, args)):
        # wrapping changed nothing: the dispatch probe already recorded
        # the condition ops — do not duplicate them in the parent block
        pre = probe
    else:
        # python scalars got wrapped, so the probe's recorded cond ops
        # read baked constants and MUST be recomputed over the carried
        # tensors; the probe's ops stay as dead code the export path
        # prunes (prune_program backward slice)
        pre = cond_fn(*vals)  # recorded in the parent block
    cond_name = rec.ensure_name(pre)

    sub = rec.begin_sub_block()
    new_vals = body_fn(*vals)
    if len(new_vals) != len(vals):
        raise TypeError(
            f"converted loop body returned {len(new_vals)} values, "
            f"expected {len(vals)}")
    new_cond = cond_fn(*new_vals)
    # write-back is a PARALLEL assignment: a body like `i = it; it += 1`
    # hands var i the tensor previously NAMED it, so all new values are
    # copied to temps before any carried name is overwritten
    updates = []
    for old_name, nv in zip(var_names, new_vals):
        if old_name is None:
            continue  # UNDEF never materialized: not carried
        updates.append((rec.ensure_name(_wrap_tensor(nv)), old_name))
    updates.append((rec.ensure_name(_wrap_tensor(new_cond)), cond_name))
    staged = []
    for nv_name, old_name in updates:
        if nv_name == old_name:
            continue
        tmp = unique_name.generate("whilewb")
        rec.block.create_var(name=tmp, shape=(), dtype="float32")
        rec.block.append_op("assign", {"X": [nv_name]}, {"Out": [tmp]}, {})
        staged.append((tmp, old_name))
    for tmp, old_name in staged:
        rec.block.append_op("assign", {"X": [tmp]}, {"Out": [old_name]}, {})
    rec.end_sub_block(parent)

    carried = [cond_name] + [n for n in var_names if n is not None]
    parent.append_op("while", {"X": carried, "Condition": [cond_name]},
                     {"Out": list(carried)}, {"sub_block": sub.idx})

    # finish the EAGER computation unrecorded: the trace holds one body;
    # the value flowing onward must be the true fixed point
    if not _truth(pre):
        final = vals
    else:
        final = tuple(new_vals)
        with _suspend_trace():
            while _truth(cond_fn(*final)):
                final = tuple(body_fn(*final))
    outs = []
    for v, n in zip(final, var_names):
        if n is None or isinstance(v, _Undefined):
            outs.append(v)
            continue
        nt = _fresh_like(_wrap_tensor(v))
        rec.bind(nt, n)
        outs.append(nt)
    return tuple(outs)


def _eager_logical(op_type, x, y=None):
    from . import eager

    ins = {"X": _wrap_tensor(x)}
    if y is not None:
        ins["Y"] = _wrap_tensor(y)
    return eager.run_op(op_type, ins)["Out"]


def convert_logical_and(lhs_fn, rhs_fn):
    l = lhs_fn() if callable(lhs_fn) else lhs_fn
    if _is_static_var(l):
        from .. import layers

        return layers.logical_and(l, rhs_fn())
    if _is_dytensor(l):
        return _eager_logical("logical_and", l, rhs_fn())
    return rhs_fn() if _truth(l) else l


def convert_logical_or(lhs_fn, rhs_fn):
    l = lhs_fn() if callable(lhs_fn) else lhs_fn
    if _is_static_var(l):
        from .. import layers

        return layers.logical_or(l, rhs_fn())
    if _is_dytensor(l):
        return _eager_logical("logical_or", l, rhs_fn())
    return l if _truth(l) else rhs_fn()


def convert_logical_not(x):
    if _is_static_var(x):
        from .. import layers

        return layers.logical_not(x)
    if _is_dytensor(x):
        return _eager_logical("logical_not", x)
    return not _truth(x)


def assert_plain_if(pred):
    """Truth-test for an if/else left in python form because its return
    shape cannot convert: LOUD when the condition is actually a traced
    tensor (silently baking one branch is worse than an error)."""
    if _tracing() is not None and _is_dytensor(pred):
        raise NotImplementedError(
            "to_static cannot convert an early `return` inside an "
            "if/else over a TENSOR condition unless both branches end "
            "in a return statement; restructure the early return")
    return _truth(pred)


def to_bool(x):
    """Eager truth value for the real break/continue guards kept inside
    python container loops (tensors evaluate eagerly)."""
    return _truth(x)


def convert_iterable(it):
    """for-over-tensor support (reference dygraph_to_static/
    break_continue_transformer.py:31 ForToWhileTransformer +
    list_transformer.py:90 list semantics): a tensor with a static
    leading dim iterates as its rows.  TPU-native design: static shapes
    make UNROLLING the idiomatic lowering — each row access records a
    slice, XLA sees a flat op sequence it can fuse, and python-list
    accumulation (append in the loop, concat/stack after) works
    unchanged because the list lives at trace time."""
    if _is_static_var(it) or _is_dytensor(it):
        shape = getattr(it, "shape", None)
        if not shape or shape[0] is None or int(shape[0]) < 0:
            raise NotImplementedError(
                "to_static can only iterate a tensor whose leading "
                "dimension is static; got shape " + repr(shape))
        n = int(shape[0])
        if _is_static_var(it):
            from .. import layers

            return [layers.squeeze(
                layers.slice(it, axes=[0], starts=[k], ends=[k + 1]),
                axes=[0]) for k in range(n)]
        # dygraph: rows come from the IR slice op (run_op records it on
        # an active trace — plain jnp indexing would be trace-invisible
        # and bake the traced input's rows as constants)
        from .eager import run_op

        return [run_op("slice", {"Input": it},
                       {"axes": [0], "starts": [k], "ends": [k + 1],
                        "decrease_axis": [0]})["Out"]
                for k in range(n)]
    return it


def init_loop_var(caller_locals, name, default):
    """Initial carry for a for-range loop variable: python leaves a
    pre-existing variable untouched when the range is empty, so reuse
    the current binding when one exists."""
    if name in caller_locals:
        return caller_locals[name]
    return default


def range_cond(i, stop, step):
    """Loop-continuation test for a ``for i in range(...)`` rewrite."""
    if isinstance(step, (int, float)):
        up = step > 0
    else:
        up = _truth(step > 0)  # tensor step: sign fixed at trace time
    return (i < stop) if up else (i > stop)


# ---------------------------------------------------------------------------
# AST transformation
# ---------------------------------------------------------------------------

def _assigned_names(stmts) -> List[str]:
    names: set = set()

    class V(ast.NodeVisitor):
        def _tgt(self, t):
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    self._tgt(e)

        def visit_Assign(self, n):
            for t in n.targets:
                self._tgt(t)
            self.generic_visit(n)

        def visit_AugAssign(self, n):
            self._tgt(n.target)
            self.generic_visit(n)

        def visit_AnnAssign(self, n):
            if n.value is not None:
                self._tgt(n.target)
            self.generic_visit(n)

        def visit_For(self, n):
            self._tgt(n.target)
            self.generic_visit(n)

        def visit_FunctionDef(self, n):
            names.add(n.name)  # the def binds its name; don't descend

        visit_AsyncFunctionDef = visit_FunctionDef

    v = V()
    for s in stmts:
        v.visit(s)
    return sorted(names)


_GEN_PREFIXES = ("_pt_t_", "_pt_f_", "_pt_wc_", "_pt_wb_", "_pt_void_")


def _user_names(names):
    """Drop the converter's own generated function/temp names."""
    return [n for n in names if not n.startswith(_GEN_PREFIXES)]


def _contains_break_or_continue(stmts) -> bool:
    """break/continue belonging to THIS loop level (nested loops and
    function defs own theirs)."""
    def scan(node) -> bool:
        if isinstance(node, (ast.Break, ast.Continue)):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.For, ast.While)):
            return False
        return any(scan(c) for c in ast.iter_child_nodes(node))

    return any(scan(s) for s in stmts)


def _contains_return(stmts) -> bool:
    """True if a `return` occurs at THIS function's level — nested
    function defs (incl. converted _pt_* branch functions) open their
    own scope and must not count."""
    def scan(node) -> bool:
        if isinstance(node, ast.Return):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return False
        return any(scan(c) for c in ast.iter_child_nodes(node))

    return any(scan(s) for s in stmts)


def _parse_stmts(src: str):
    return ast.parse(textwrap.dedent(src)).body


def _indent(stmts, pad="    "):
    if not stmts:
        return pad + "pass"
    return textwrap.indent("\n".join(ast.unparse(s) for s in stmts), pad)


class _BreakContinueRewriter:
    """Reference break_continue_transformer.py: break/continue inside a
    loop body become flag assignments; trailing statements get wrapped
    in a not-flagged guard."""

    def __init__(self, n):
        self.brk = f"_pt_brk_{n}"
        self.cont = f"_pt_cont_{n}"
        self.brk_used = False
        self.cont_used = False

    def guard_expr(self) -> str:
        flags = []
        if self.brk_used:
            flags.append(self.brk)
        if self.cont_used:
            flags.append(self.cont)
        if len(flags) == 2:
            inner = (f"_jst.convert_logical_or(lambda: {flags[0]}, "
                     f"lambda: {flags[1]})")
        else:
            inner = flags[0]
        return f"_jst.convert_logical_not({inner})"

    def rewrite(self, stmts):
        """Each break/continue site guards its OWN remainder (nested
        guards, like the reference's per-region wrapping) so a second
        site firing mid-guard still skips the statements after it."""
        out = []
        for idx, st in enumerate(stmts):
            st2, h = self._stmt(st)
            out.extend(st2 if isinstance(st2, list) else [st2])
            if h:
                rest, _ = self.rewrite(stmts[idx + 1:])
                if rest:
                    guard = ast.parse(
                        f"if {self.guard_expr()}:\n    pass").body[0]
                    guard.body = rest
                    out.append(guard)
                return out, True
        return out, False

    def _stmt(self, st):
        if isinstance(st, ast.Break):
            self.brk_used = True
            return _parse_stmts(f"{self.brk} = True"), True
        if isinstance(st, ast.Continue):
            self.cont_used = True
            return _parse_stmts(f"{self.cont} = True"), True
        if isinstance(st, ast.If):
            body, h1 = self.rewrite(st.body)
            orelse, h2 = (self.rewrite(st.orelse) if st.orelse
                          else ([], False))
            if h1 or h2:
                new = ast.If(test=st.test, body=body, orelse=orelse)
                return ast.copy_location(new, st), True
            return st, False
        # nested loops own their break/continue; defs open a new scope
        return st, False


def _legacy_return_ok(stmts) -> bool:
    """True when every `return` already sits where the direct conversion
    handles it: the block's final statement, or a tail-position if/else
    whose BOTH branches end in return.  Anything else (return in a loop,
    guard-style early return, mixed forms) goes through _ReturnRewriter.
    """
    for i, s in enumerate(stmts):
        if not _contains_return([s]):
            continue
        tail = i == len(stmts) - 1
        if isinstance(s, ast.Return):
            if not tail:
                return False
        elif isinstance(s, ast.If):
            if not (tail and s.body and s.orelse
                    and isinstance(s.body[-1], ast.Return)
                    and isinstance(s.orelse[-1], ast.Return)
                    and _legacy_return_ok(s.body[:-1] or [])
                    and _legacy_return_ok(s.orelse[:-1] or [])):
                return False
        else:
            return False
    return True


class _ReturnRewriter:
    """Reference dygraph_to_static/return_transformer.py:135, in a form
    that fits the trace machinery: each return SITE k becomes a boolean
    flag assignment ``_pt_ret_f<k> = True`` (plus ``break`` inside
    loops — the loop converter folds it into the loop condition for
    tensor flags); statements after a possibly-returning construct are
    guarded by ``not (f1 or f2 or ...)``; and the function closes with
    ONE nested select ``ret_select(f1, e1, ret_select(f2, e2, tail))``
    that re-evaluates each site's expression at function end.

    Why flags-only (no carried return VALUE): a carried value would
    need a typed initial placeholder before the first loop, which is
    unknowable statically.  Re-evaluating e_k at the end is sound
    because once a flag fires every later statement is guarded, so the
    variables e_k reads still hold their values from the firing point
    (loop vars exit through the normal carry)."""

    def __init__(self):
        self.flags: List[str] = []
        self.sites: List = []  # [(flag, expr_src)] in program order
        self.tail_expr = "None"

    def _fired(self):
        return " or ".join(self.flags) if self.flags else "False"

    def rewrite_function(self, fdef):
        body = self._block(list(fdef.body), in_loop=False, top=True)
        init = _parse_stmts(
            "\n".join(f"{f} = False" for f in self.flags))
        ret = "(" + self.tail_expr + ")"
        for f, e in reversed(self.sites):
            ret = (f"_jst.ret_select({f}, lambda: ({e}), "
                   f"lambda: {ret})")
        fdef.body = init + body + _parse_stmts(f"return {ret}")

    def _block(self, stmts, in_loop, top=False):
        out = []
        for idx, s in enumerate(stmts):
            if isinstance(s, ast.Return):
                expr = ast.unparse(s.value) if s.value is not None \
                    else "None"
                if top and idx == len(stmts) - 1:
                    self.tail_expr = expr  # the default select leg
                    return out
                flag = f"_pt_ret_f{len(self.flags) + 1}"
                self.flags.append(flag)
                self.sites.append((flag, expr))
                out += _parse_stmts(f"{flag} = True")
                if in_loop:
                    out.append(ast.Break())
                return out  # statements after `return` are unreachable
            if not _contains_return([s]):
                out.append(s)
                continue
            if isinstance(s, ast.If):
                s.body = self._block(s.body, in_loop)
                s.orelse = self._block(s.orelse, in_loop)
            elif isinstance(s, (ast.While, ast.For)):
                s.body = self._block(s.body, in_loop=True)
            else:
                raise NotImplementedError(
                    f"to_static does not support `return` inside a "
                    f"{type(s).__name__.lower()} block")
            out.append(s)
            if in_loop:
                # the construct may have fired a return: exit the
                # ENCLOSING loop too
                out += _parse_stmts(f"if {self._fired()}:\n    break")
            rest = self._block(list(stmts[idx + 1:]), in_loop, top=top)
            if rest:
                guard = ast.parse(
                    f"if _jst.convert_logical_not({self._fired()}):\n"
                    f"    pass").body[0]
                guard.body = rest
                out.append(guard)
            return out
        return out


def _is_append_stmt(s):
    return (isinstance(s, ast.Expr) and isinstance(s.value, ast.Call)
            and isinstance(s.value.func, ast.Attribute)
            and s.value.func.attr == "append"
            and isinstance(s.value.func.value, ast.Name)
            and len(s.value.args) == 1 and not s.value.keywords)


def _branch_appends(stmts):
    """Top-level ``name.append(expr)`` statements: [(list_name, idx)]."""
    return [(s.value.func.value.id, i) for i, s in enumerate(stmts)
            if _is_append_stmt(s)]


def _replace_append(stmts, lname, tmp):
    """Swap the first top-level ``lname.append(e)`` for ``tmp = e``."""
    for i, s in enumerate(stmts):
        if _is_append_stmt(s) and s.value.func.value.id == lname:
            stmts[i] = ast.copy_location(
                _parse_stmts(f"{tmp} = {ast.unparse(s.value.args[0])}")[0],
                s)
            return


class _Dy2StaticTransformer(ast.NodeTransformer):
    def __init__(self):
        self.n = 0

    def _next(self):
        self.n += 1
        return self.n

    # -- boolean ops --------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        conv = ("convert_logical_and" if isinstance(node.op, ast.And)
                else "convert_logical_or")
        expr = ast.unparse(node.values[0])
        for v in node.values[1:]:
            expr = f"_jst.{conv}(lambda: ({expr}), lambda: " \
                   f"({ast.unparse(v)}))"
        return ast.parse(expr, mode="eval").body

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.parse(
                f"_jst.convert_logical_not({ast.unparse(node.operand)})",
                mode="eval").body
        return node

    # -- if/else ------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        i = self._next()
        has_ret = _contains_return(node.body) or _contains_return(node.orelse)
        outs = sorted(set(_user_names(_assigned_names(node.body)))
                      | set(_user_names(_assigned_names(node.orelse))))
        arglist = ", ".join(outs)
        names_lit = repr(tuple(outs))
        test_src = ast.unparse(node.test)

        if has_ret:
            def last_is_return(stmts):
                return (bool(stmts) and isinstance(stmts[-1], ast.Return)
                        and not _contains_return(stmts[:-1]))

            if not (last_is_return(node.body) and last_is_return(node.orelse)):
                # guard-style early return (`if cond: return x`): keep
                # plain python, but the shimmed test raises if the
                # condition turns out to be a traced tensor — a python
                # guard keeps working, a data-dependent one stays LOUD
                # instead of silently baking one branch
                guarded = ast.parse(
                    f"if _jst.assert_plain_if(({test_src})):\n    pass"
                ).body[0]
                guarded.body = node.body
                guarded.orelse = node.orelse
                return ast.copy_location(guarded, node)
            t_ret = ast.unparse(node.body[-1].value) \
                if node.body[-1].value is not None else "None"
            f_ret = ast.unparse(node.orelse[-1].value) \
                if node.orelse[-1].value is not None else "None"
            src = (
                f"def _pt_t_{i}({arglist}):\n"
                f"{_indent(node.body[:-1])}\n"
                f"    return {t_ret}\n"
                f"def _pt_f_{i}({arglist}):\n"
                f"{_indent(node.orelse[:-1])}\n"
                f"    return {f_ret}\n"
                f"return _jst.convert_ifelse(({test_src}), _pt_t_{i}, "
                f"_pt_f_{i}, {names_lit}, locals(), returning=True)\n"
            )
            return _parse_stmts(src)

        # list_transformer role (reference list_transformer.py:90):
        # symmetric `L.append(e)` in both branches hoists to a merged
        # temp assigned in each branch + ONE append after the merge, so
        # the appended value is a parent-block cond output instead of a
        # sub-block temp the rest of the graph cannot read
        post = []
        appends_t = _branch_appends(node.body)
        appends_f = _branch_appends(node.orelse)
        if appends_t and [a[0] for a in appends_t] == \
                [a[0] for a in appends_f]:
            for k, ((lname, _), _) in enumerate(zip(appends_t, appends_f)):
                tmp = f"_pt_app_{i}_{k}"
                _replace_append(node.body, lname, tmp)
                _replace_append(node.orelse, lname, tmp)
                post += _parse_stmts(f"{lname}.append({tmp})")
            outs = sorted(set(outs)
                          | {f"_pt_app_{i}_{k}"
                             for k in range(len(appends_t))})
            arglist = ", ".join(outs)
            names_lit = repr(tuple(outs))

        ret_tuple = "(" + ", ".join(outs) + ("," if len(outs) == 1 else "") \
            + ")" if outs else "()"
        target = ret_tuple if outs else "_pt_void_%d" % i
        src = (
            f"def _pt_t_{i}({arglist}):\n"
            f"{_indent(node.body)}\n"
            f"    return {ret_tuple}\n"
            f"def _pt_f_{i}({arglist}):\n"
            f"{_indent(node.orelse)}\n"
            f"    return {ret_tuple}\n"
            f"{target} = _jst.convert_ifelse(({test_src}), _pt_t_{i}, "
            f"_pt_f_{i}, {names_lit}, locals())\n"
        )
        return _parse_stmts(src) + post

    # -- loops --------------------------------------------------------
    def _build_while(self, i, test_src, body_stmts, init_src, outs):
        arglist = ", ".join(outs)
        names_lit = repr(tuple(outs))
        ret_tuple = "(" + ", ".join(outs) + ("," if len(outs) == 1 else "") \
            + ")"
        src = (
            (init_src + "\n" if init_src else "")
            + f"def _pt_wc_{i}({arglist}):\n"
            f"    return ({test_src})\n"
            f"def _pt_wb_{i}({arglist}):\n"
            f"{_indent(body_stmts)}\n"
            f"    return {ret_tuple}\n"
            f"{ret_tuple} = _jst.convert_while_loop(_pt_wc_{i}, "
            f"_pt_wb_{i}, {names_lit}, locals())\n"
        )
        return _parse_stmts(src)

    def visit_While(self, node):
        if node.orelse:
            raise NotImplementedError(
                "to_static does not support while/else")
        if _contains_return(node.body):
            raise NotImplementedError(
                "to_static does not support `return` inside a converted "
                "while loop body; assign to a variable and return after "
                "the loop")
        i = self._next()
        rw = _BreakContinueRewriter(i)
        body, _ = rw.rewrite(node.body)
        test_src = ast.unparse(node.test)
        init = []
        if rw.brk_used:
            init.append(f"{rw.brk} = False")
            test_src = (f"_jst.convert_logical_and(lambda: "
                        f"_jst.convert_logical_not({rw.brk}), "
                        f"lambda: ({test_src}))")
        if rw.cont_used:
            init.append(f"{rw.cont} = False")
            body = _parse_stmts(f"{rw.cont} = False") + body

        # convert nested constructs (incl. the guards just created)
        wrapper = ast.Module(body=body, type_ignores=[])
        wrapper = self.generic_visit(wrapper)
        body = wrapper.body
        test_node = ast.parse(test_src, mode="eval").body
        test_node = self.visit(test_node)
        test_src = ast.unparse(test_node)

        outs = _user_names(_assigned_names(body))
        if not outs:
            raise NotImplementedError(
                "converted while loop assigns no variables; a loop whose "
                "body has only side effects cannot become a static op")
        return self._build_while(i, test_src, body, "\n".join(init), outs)

    def visit_For(self, node):
        if node.orelse:
            raise NotImplementedError("to_static does not support for/else")
        it = node.iter
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range"):
            # tensors iterate as their rows (convert_iterable unrolls a
            # static leading dim); python containers pass through.
            # Either way the loop stays a python loop whose body still
            # converts (tensor ifs must not bake).  A raw
            # break/continue cannot move into a generated branch
            # function (SyntaxError), so rewrite them into flags first
            # and emit REAL break/continue at the loop-body top level,
            # guarded by the (possibly tensor-valued) flags.
            node.iter = ast.parse(
                f"_jst.convert_iterable({ast.unparse(it)})",
                mode="eval").body
            if _contains_break_or_continue(node.body):
                i = self._next()
                rw = _BreakContinueRewriter(i)
                body, _ = rw.rewrite(node.body)
                wrapper = ast.Module(body=body, type_ignores=[])
                wrapper = self.generic_visit(wrapper)
                body = wrapper.body
                pre = []
                if rw.cont_used:
                    body = _parse_stmts(f"{rw.cont} = False") + body
                if rw.brk_used:
                    pre.append(f"{rw.brk} = False")
                    body = body + _parse_stmts(
                        f"if _jst.to_bool({rw.brk}):\n    break")
                node.body = body
                init = _parse_stmts("\n".join(pre)) if pre else []
                return init + [node]
            self.generic_visit(node)
            return node
        if not isinstance(node.target, ast.Name):
            raise NotImplementedError(
                "to_static for-range needs a simple loop variable")
        if _contains_return(node.body):
            raise NotImplementedError(
                "to_static does not support `return` inside a converted "
                "for-range loop body; assign to a variable and return "
                "after the loop")
        i = self._next()
        var = node.target.id
        a = [ast.unparse(x) for x in it.args]
        if len(a) == 1:
            start, stop, step = "0", a[0], "1"
        elif len(a) == 2:
            start, stop, step = a[0], a[1], "1"
        else:
            start, stop, step = a[0], a[1], a[2]

        rw = _BreakContinueRewriter(i)
        body, _ = rw.rewrite(node.body)
        # python for semantics: the loop variable holds the CURRENT
        # iteration's value (and keeps it after break/exhaustion), so an
        # internal iterator carries the next position and the loop var is
        # assigned at body start
        it = f"_pt_it_{i}"
        init = [f"{var} = _jst.init_loop_var(locals(), {var!r}, ({start}))",
                f"{it} = {start}",
                f"_pt_lim_{i} = {stop}", f"_pt_step_{i} = {step}"]
        test_src = f"_jst.range_cond({it}, _pt_lim_{i}, _pt_step_{i})"
        if rw.brk_used:
            init.append(f"{rw.brk} = False")
            test_src = (f"_jst.convert_logical_and(lambda: "
                        f"_jst.convert_logical_not({rw.brk}), "
                        f"lambda: ({test_src}))")
        if rw.cont_used:
            init.append(f"{rw.cont} = False")
            body = _parse_stmts(f"{rw.cont} = False") + body
        body = _parse_stmts(f"{var} = {it}\n"
                            f"{it} = {it} + _pt_step_{i}") + body

        wrapper = ast.Module(body=body, type_ignores=[])
        wrapper = self.generic_visit(wrapper)
        body = wrapper.body

        outs = _user_names(_assigned_names(body) + [var])
        outs = sorted(set(outs) | {it, f"_pt_lim_{i}", f"_pt_step_{i}"})
        return self._build_while(i, test_src, body, "\n".join(init), outs)


def convert_callable(obj):
    """Entry point used by the trace machinery: functions and bound
    methods convert directly; Layer-like objects convert their
    ``forward`` (reference StaticFunction over Layer.forward) while
    still dispatching through ``__call__`` so forward pre/post hooks
    keep running."""
    if inspect.isfunction(obj) or inspect.ismethod(obj):
        return convert_to_static(obj)
    fwd = getattr(obj, "forward", None)
    if fwd is not None and inspect.ismethod(fwd):
        conv = convert_to_static(fwd)
        if conv is not fwd:
            def call(*a, **k):
                obj.forward = conv  # instance attr shadows the method
                try:
                    return obj(*a, **k)
                finally:
                    del obj.forward

            call.__wrapped_original__ = obj
            return call
    return obj


def convert_to_static(fn):
    """Rewrite fn's AST; returns the converted function (or fn itself if
    the source is unavailable, e.g. a builtin or REPL lambda)."""
    base = fn
    bound_self = getattr(fn, "__self__", None)
    if bound_self is not None:
        base = fn.__func__
    try:
        src = textwrap.dedent(inspect.getsource(base))
    except (OSError, TypeError):
        return fn
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []  # strip @to_static etc. (reference does too)
    if _contains_return(fdef.body) and not _legacy_return_ok(fdef.body):
        _ReturnRewriter().rewrite_function(fdef)
    _Dy2StaticTransformer().visit(fdef)
    ast.fix_missing_locations(tree)

    glb = dict(base.__globals__)
    if base.__closure__:
        glb.update(zip(base.__code__.co_freevars,
                       (c.cell_contents for c in base.__closure__)))
    import paddle_tpu.dygraph.dy2static as _jst_mod

    glb["_jst"] = _jst_mod
    code = compile(tree, filename=f"<to_static {base.__name__}>",
                   mode="exec")
    ns: dict = {}
    exec(code, glb, ns)
    out = ns[fdef.name]
    out.__wrapped_original__ = fn
    if bound_self is not None:
        out = out.__get__(bound_self)
    return out
