"""Trace-based dygraph->static export: TracedLayer / to_static / jit.save.

Role parity: reference python/paddle/fluid/dygraph/jit.py (``save``:466,
``TracedLayer``:995) over the C++ ``ProgramDescTracer`` (imperative/jit/).
TPU-native: eager dispatch already funnels every op through
``eager.run_op`` with IR op names/slots/attrs, so tracing is just
recording each eager op into a ``Program`` as it runs — no AST transforms
needed for the trace path.  The exported program feeds the compile-once
``inference.Predictor`` / ``fluid.io.save_inference_model`` machinery, so
dygraph-train -> trace -> serve round-trips inside one framework.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..framework import dtypes, unique_name
from ..framework.program import Program, program_guard
from .tensor import Tensor

# The active recorder lives in eager._TRACE_REC (one trace at a time,
# like the reference's ProgramDescTracer guard) so the eager hot path
# checks a plain module global instead of importing this module per op.


class _ProgramRecorder:
    """Records eager ops into a Program while they execute."""

    def __init__(self):
        self.program = Program()
        self.block = self.program.global_block
        self._names: Dict[int, str] = {}  # id(Tensor) -> var name
        # id() is only unique while the object lives: hold a reference to
        # every traced tensor or a GC'd intermediate's recycled id would
        # alias a later tensor to a stale var (the reference
        # ProgramDescTracer holds VarBase refs for the same reason)
        self._keep: List[Tensor] = []
        self.feed_names: List[str] = []
        self.param_values: Dict[str, np.ndarray] = {}

    # -- var management -----------------------------------------------
    def declare_input(self, t: Tensor) -> str:
        name = unique_name.generate("trace_feed")
        self.block.create_var(name=name, shape=list(t.shape),
                              dtype=str(np.dtype(t._value.dtype)),
                              stop_gradient=True)
        self._names[id(t)] = name
        self._keep.append(t)
        self.feed_names.append(name)
        return name

    def _var_for(self, t: Tensor) -> str:
        name = self._names.get(id(t))
        if name is not None:
            return name
        # first sighting mid-trace: a parameter or a captured constant —
        # either way it becomes persistable state saved with the model.
        # Declared in the ROOT block even when captured inside a
        # cond/while sub-block: persistable state is global, and the
        # export path only saves root-block vars.
        if getattr(t, "persistable", False) and t.name:
            name = t.name
        else:
            name = unique_name.generate("trace_const")
        self.program.global_block.create_var(
            name=name, shape=list(t.shape),
            dtype=str(np.dtype(t._value.dtype)),
            persistable=True, stop_gradient=True)
        self._names[id(t)] = name
        self._keep.append(t)
        self.param_values[name] = np.asarray(t._value)
        return name

    def _out_var(self, t: Tensor) -> str:
        name = unique_name.generate("trace_tmp")
        self.block.create_var(name=name, shape=list(t.shape),
                              dtype=str(np.dtype(t._value.dtype)),
                              stop_gradient=False)
        self._names[id(t)] = name
        self._keep.append(t)
        return name

    def alias(self, produced: Tensor, holder: Tensor):
        """trace_op-style value hand-off: ``holder`` now carries the value
        ``produced`` had; later ops reference ``holder``."""
        if id(produced) in self._names:
            self._names[id(holder)] = self._names[id(produced)]
            self._keep.append(holder)

    def name_of(self, t: Tensor) -> Optional[str]:
        return self._names.get(id(t))

    # -- control-flow capture (dy2static convert shims) ----------------
    def ensure_name(self, t: Tensor) -> str:
        """Var name for ``t``, registering it as a captured constant if
        the trace has not seen it (same policy as op-input capture)."""
        return self._var_for(t)

    def bind(self, t: Tensor, name: str):
        """Re-point ``t`` at ``name`` (e.g. a cond/while output var)."""
        self._names[id(t)] = name
        self._keep.append(t)

    def new_parent_var(self, parent, t: Tensor) -> str:
        name = unique_name.generate("ctrl_out")
        parent.create_var(name=name, shape=list(t.shape),
                          dtype=str(np.dtype(t._value.dtype)),
                          stop_gradient=False)
        return name

    def begin_sub_block(self):
        sub = self.program._create_block()
        self.block = sub
        return sub

    def end_sub_block(self, parent):
        self.program._rollback()
        self.block = parent

    # -- op recording --------------------------------------------------
    def record(self, op_type: str, tensor_inputs: Dict[str, List[Tensor]],
               attrs: dict, result: Dict[str, object],
               out_slots: Sequence[str]):
        in_names = {slot: [self._var_for(t) for t in ts]
                    for slot, ts in tensor_inputs.items()}
        out_names: Dict[str, List[str]] = {}
        for slot in out_slots:
            v = result.get(slot)
            ts = v if isinstance(v, (list, tuple)) else [v]
            out_names[slot] = [self._out_var(t) for t in ts if t is not None]
        self.block.append_op(op_type, in_names, out_names, dict(attrs or {}))


def _recorder() -> Optional[_ProgramRecorder]:
    from . import eager

    return eager._TRACE_REC


class _trace_guard:
    def __init__(self, rec):
        self.rec = rec

    def __enter__(self):
        from . import eager

        if eager._TRACE_REC is not None:
            raise RuntimeError("a dygraph trace is already active")
        eager._TRACE_REC = self.rec
        return self.rec

    def __exit__(self, *exc):
        from . import eager

        eager._TRACE_REC = None
        return False


def _as_tensors(inputs):
    ts = []
    for x in inputs:
        if isinstance(x, Tensor):
            ts.append(x)
        else:
            ts.append(Tensor(np.asarray(x)))
    return ts


def trace(layer_or_fn, inputs):
    """Run ``layer_or_fn(*inputs)`` once, recording every op into a
    Program.  Returns (outputs, recorder).

    The callable is AST-converted first (dy2static), so python
    ``if``/``while``/``for`` over tensor values record real
    cond/while ops instead of baking in the traced branch."""
    from .dy2static import convert_callable

    layer_or_fn = convert_callable(layer_or_fn)
    inputs = _as_tensors(list(inputs))
    rec = _ProgramRecorder()
    for t in inputs:
        rec.declare_input(t)
    with _trace_guard(rec):
        outs = layer_or_fn(*inputs)
    flat = outs if isinstance(outs, (list, tuple)) else [outs]
    fetch = []
    for o in flat:
        name = rec.name_of(o)
        if name is None:
            raise RuntimeError(
                "trace output was not produced by recorded ops (did the "
                "forward use a non-IR escape hatch like numpy indexing?)")
        fetch.append(name)
    return outs, rec, fetch


class TracedLayer:
    """Reference fluid.dygraph.TracedLayer (jit.py:995): trace once, then
    run / export the static program."""

    def __init__(self, program, feed_names, fetch_names, param_values):
        self.program = program
        self._feed_names = list(feed_names)
        self._fetch_names = list(fetch_names)
        self._param_values = dict(param_values)
        self._exe = None
        self._scope = None

    @staticmethod
    def trace(layer, inputs):
        outs, rec, fetch = trace(layer, inputs)
        tl = TracedLayer(rec.program, rec.feed_names, fetch, rec.param_values)
        return outs, tl

    def _ensure_exe(self):
        import paddle_tpu as pt

        if self._exe is None:
            self._exe = pt.Executor(pt.framework.place._default_place())
            self._scope = pt.framework.Scope()
            for name, val in self._param_values.items():
                self._scope.set_var(name, val)
        return self._exe, self._scope

    def __call__(self, *inputs):
        exe, scope = self._ensure_exe()
        feed = {n: (t._value if isinstance(t, Tensor) else np.asarray(t))
                for n, t in zip(self._feed_names, inputs)}
        outs = exe.run(self.program, feed=feed,
                       fetch_list=self._fetch_names, scope=scope,
                       return_numpy=False)
        return [Tensor(o) for o in outs]

    def save_inference_model(self, path, feed=None, fetch=None):
        """Export (program, params) servable by inference.Predictor
        (reference TracedLayer.save_inference_model)."""
        import paddle_tpu as pt
        from ..fluid import io as fluid_io

        exe, scope = self._ensure_exe()
        feed_names = ([self._feed_names[i] for i in feed]
                      if feed else self._feed_names)
        fetch_names = ([self._fetch_names[i] for i in fetch]
                       if fetch else self._fetch_names)
        from ..fluid import scope_guard

        with scope_guard(scope):
            fluid_io.save_inference_model(
                path, feed_names,
                [self.program.global_block.var(n) for n in fetch_names],
                exe, main_program=self.program)


class StaticFunction:
    """``@to_static`` wrapper: traces on first call per input signature and
    afterwards executes the compiled static program (reference
    dygraph_to_static ProgramTranslator, trace-based instead of AST)."""

    def __init__(self, fn, input_spec=None):
        from .dy2static import convert_callable

        self._fn = convert_callable(fn)
        self._input_spec = input_spec
        self._traced: Dict[tuple, TracedLayer] = {}

    def _key(self, inputs):
        return tuple((tuple(t.shape), str(np.dtype(t._value.dtype)))
                     for t in inputs)

    def __call__(self, *inputs):
        if _recorder() is not None:
            # nested inside an active trace: run the python body eagerly
            # so its ops are recorded into the OUTER program (a nested
            # trace would either deadlock the guard or hide these ops
            # behind an Executor call)
            return self._fn(*inputs)
        inputs = _as_tensors(list(inputs))
        key = self._key(inputs)
        tl = self._traced.get(key)
        if tl is None:
            _, tl = TracedLayer.trace(self._fn, inputs)
            self._traced[key] = tl
        outs = tl(*inputs)
        return outs[0] if len(outs) == 1 else outs

    @property
    def concrete_program(self):
        if not self._traced:
            raise RuntimeError("call the function once (or pass input_spec "
                               "to jit.save) before reading the program")
        return next(iter(self._traced.values()))


def to_static(fn=None, input_spec=None):
    """Decorator parity with paddle.jit.to_static (reference
    dygraph_to_static/program_translator.py declarative)."""
    if fn is None:
        return lambda f: StaticFunction(f, input_spec)
    return StaticFunction(fn, input_spec)


declarative = to_static


def _example_from_spec(spec):
    from ..hapi.model import InputSpec

    if isinstance(spec, InputSpec):
        shape = [1 if (s is None or int(s) < 0) else int(s)
                 for s in spec.shape]
        return Tensor(np.zeros(shape, dtypes.to_np(spec.dtype)))
    if isinstance(spec, Tensor):
        return spec
    return Tensor(np.asarray(spec))


def save(layer, path, input_spec=None):
    """paddle.jit.save (reference dygraph/jit.py:466): trace ``layer`` and
    export an inference model to ``path`` (dir with model+params)."""
    if isinstance(layer, StaticFunction):
        fn = layer._fn
        if input_spec is None:
            input_spec = layer._input_spec  # @to_static(input_spec=...)
    elif callable(layer):
        fn = layer
    else:
        raise TypeError(f"cannot jit.save {type(layer)}")
    if input_spec is None:
        raise ValueError(
            "jit.save needs input_spec (InputSpec list or example tensors) "
            "to trace the forward")
    inputs = [_example_from_spec(s) for s in input_spec]
    _, tl = TracedLayer.trace(fn, inputs)
    tl.save_inference_model(path)
    return tl


class TranslatedLayer:
    """Loaded counterpart of jit.save (reference TranslatedLayer): a
    callable over the compile-once Predictor."""

    def __init__(self, predictor):
        self._predictor = predictor

    def __call__(self, *inputs):
        arrays = [t._value if isinstance(t, Tensor) else np.asarray(t)
                  for t in inputs]
        outs = self._predictor.run(arrays)
        ts = [Tensor(o) for o in outs]
        return ts[0] if len(ts) == 1 else ts

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("a loaded inference program cannot be trained; "
                           "retrain from the dygraph Layer and re-save")


def load(path):
    """paddle.jit.load: inference model dir -> callable TranslatedLayer."""
    from ..inference import Config, create_predictor

    cfg = Config(path)
    return TranslatedLayer(create_predictor(cfg))
