"""`Layer`: the dygraph module base class.

Role parity: reference python/paddle/fluid/dygraph/layers.py `Layer`:63
(`__call__`:812, parameter/sublayer registries, state_dict) — the same
contract `paddle.nn.Layer` re-exports in the 2.0 API.
"""
from __future__ import annotations

import collections
from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import unique_name
from ..framework.dtypes import to_jnp
from ..initializer import (  # noqa: F401
    ConstantInitializer,
    MSRAInitializer,
    NormalInitializer,
    TruncatedNormalInitializer,
    UniformInitializer,
    XavierInitializer,
)
from ..param_attr import ParamAttr
from . import base
from .tensor import Parameter, Tensor


def _eager_initialize(init, shape, dtype, is_bias):
    """Run an initializer eagerly (the startup-program path, collapsed)."""
    if init is None:
        init = ConstantInitializer(0.0) if is_bias else XavierInitializer()
    return init.eager_value([int(s) for s in shape], dtype, base.next_eager_key())


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype: str = "float32"):
        self._full_name = unique_name.generate(
            name_scope or self.__class__.__name__.lower())
        self._dtype = dtype
        self.training = True
        self._parameters = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers = collections.OrderedDict()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()

    # -- naming ------------------------------------------------------------
    def full_name(self):
        return self._full_name

    # -- mode --------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # -- registration ------------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype
        init = (attr.initializer if attr and attr.initializer is not None
                else default_initializer)
        value = _eager_initialize(init, shape, dtype, is_bias)
        name = (attr.name if attr and attr.name
                else unique_name.generate(self._full_name + (".b" if is_bias else ".w")))
        p = Parameter(value, name=name, trainable=attr.trainable if attr else True)
        if attr:
            p.optimize_attr = {"learning_rate": attr.learning_rate}
            p.regularizer = attr.regularizer
            p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute routing ---------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
        elif buffers is not None and name in buffers:
            buffers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # -- traversal -----------------------------------------------------------
    def children(self) -> Iterator["Layer"]:
        for l in self._sub_layers.values():
            if l is not None:
                yield l

    def named_children(self):
        for n, l in self._sub_layers.items():
            if l is not None:
                yield n, l

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for l in self.children():
            out.extend(l.sublayers(include_self=True))
        return out

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else prefix + "." + name), p
        if include_sublayers:
            for lname, l in self.named_children():
                sub_prefix = prefix + "." + lname if prefix else lname
                for n, p in l.named_parameters(prefix=sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (prefix + "." + name if prefix else name), b
        if include_sublayers:
            for lname, l in self.named_children():
                sub_prefix = prefix + "." + lname if prefix else lname
                for n, b in l.named_buffers(prefix=sub_prefix):
                    yield n, b

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for lname, l in self.named_children():
            sub_prefix = prefix + "." + lname if prefix else lname
            yield from l.named_sublayers(prefix=sub_prefix, include_self=True)

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- state dict -----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix=""):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self._parameters.items():
            if p is not None:
                dest[structured_name_prefix + name] = p
        for name, b in self._buffers.items():
            if b is not None and name not in self._non_persistable_buffer_names:
                dest[structured_name_prefix + name] = b
        if include_sublayers:
            for lname, l in self.named_children():
                l.state_dict(destination=dest,
                             structured_name_prefix=structured_name_prefix + lname + ".")
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            val = v.numpy() if hasattr(v, "numpy") else np.asarray(v)
            own[k].set_value(val)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks, hook)
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks, hook)
        return handle

    # -- call -----------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{type(self).__name__}({extra}"]
        for name, l in self.named_children():
            sub = repr(l).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub}")
        return "\n".join(lines) + ")" if len(lines) > 1 else lines[0] + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def to(self, *args, **kwargs):
        return self  # single logical device; placement is XLA's job


class _HookHandle:
    _next_id = [0]

    def __init__(self, store, hook):
        self._store = store
        self._id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1
        store[self._id] = hook

    def remove(self):
        self._store.pop(self._id, None)
