"""Dygraph (eager) runtime.

Role parity: reference paddle/fluid/imperative/ (§2.3 of SURVEY.md) +
python/paddle/fluid/dygraph/.  Eager execution on jax arrays reusing the
static path's op lowering rules; autograd by VJP replay.
"""
from . import base  # noqa: F401
from .backward import grad, run_backward  # noqa: F401
from .base import (  # noqa: F401
    enable_grad,
    enabled,
    guard,
    in_dygraph_mode,
    no_grad,
    seed,
    to_variable,
)
from .eager import Tracer, apply_jax, run_op, tracer  # noqa: F401
from .layers import Layer  # noqa: F401
from .tensor import Parameter, Tensor  # noqa: F401
