"""Eager op dispatch: run a registered lowering rule immediately.

Role parity: reference imperative/tracer.cc `Tracer::TraceOp` +
prepared_operator.cc `PreparedOp::Run` + the generated `core.ops.*` fast
path (pybind/op_function_generator.cc:227).  TPU-native: there is no
kernel choice — the op's lowering rule (the SAME rule the static XLA
executor traces) runs eagerly on jax arrays, and if gradients are enabled
a VJP-replay TapeNode is recorded (see backward.py, the BasicEngine
equivalent).  One op implementation serves both execution modes, which is
how eager/static parity holds by construction.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from builtins import all as builtins_all

from ..framework.lowering import LoweringContext, get_lowering
from . import base
from .tensor import Tensor

# default output slot names per op family; ops not listed produce "Out".
_OUT_SLOTS: Dict[str, Sequence[str]] = {
    "batch_norm": ("Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"),
    "sync_batch_norm": ("Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"),
    "layer_norm": ("Y", "Mean", "Variance"),
    "group_norm": ("Y", "Mean", "Variance"),
    "instance_norm": ("Y", "SavedMean", "SavedVariance"),
    "softmax_with_cross_entropy": ("Loss", "Softmax"),
    "top_k": ("Out", "Indices"),
    "top_k_v2": ("Out", "Indices"),
    "argsort": ("Out", "Indices"),
    "dropout": ("Out", "Mask"),
    "reshape2": ("Out", "XShape"),
    "transpose2": ("Out", "XShape"),
    "squeeze2": ("Out", "XShape"),
    "unsqueeze2": ("Out", "XShape"),
    "flatten2": ("Out", "XShape"),
    "unstack": ("Y",),
    "split": ("Out",),
    "check_finite_and_unscale": ("Out", "FoundInfinite"),
    "update_loss_scaling": ("Out", "LossScaling", "OutGoodSteps", "OutBadSteps"),
    "accuracy": ("Accuracy", "Correct", "Total"),
    "relu": ("Out",),
}

# ops whose listed output slot is a LIST with the same length as input list
_LIST_OUT_OPS = {"split": "Out", "unstack": "Y", "meshgrid": "Out",
                 "check_finite_and_unscale": "Out"}

# active dygraph->static program recorder (set by jit._trace_guard)
_TRACE_REC = None


# bound on first use (amp imports the framework; keep eager import-light)
_AMP_STATE = None


def _amp_policy(op_type):
    """Dygraph autocast policy (reference imperative/amp_auto_cast.cc
    NeedCast:51): returns (cast_dtype_or_None, gray_follow_dtype_or_None)
    CAPTURED AT RECORD TIME — backward replay outside the auto_cast scope
    must cast exactly as the forward did.  Casting happens INSIDE the
    recorded fwd closure so vjp differentiates through it (grads reach
    fp32 master params)."""
    global _AMP_STATE
    if _AMP_STATE is None:
        from ..amp import amp_state

        _AMP_STATE = amp_state()
    st = _AMP_STATE
    if not st.enabled:
        return None, None
    if op_type in st.lists.white_list:
        return st.dtype, None
    if op_type in st.lists.black_list:
        return "float32", None
    if op_type in getattr(st.lists, "gray_follow_cast", ()):
        return None, st.dtype
    return None, None


class _EagerOp:
    """Duck-typed Operator (framework/program.py:174) for eager dispatch."""

    __slots__ = ("type", "inputs", "outputs", "attrs")

    def __init__(self, type, inputs, outputs, attrs):
        self.type = type
        self.inputs = inputs
        self.outputs = outputs
        self.attrs = dict(attrs or {})

    def input(self, slot):
        return list(self.inputs.get(slot, []))

    def output(self, slot):
        return list(self.outputs.get(slot, []))

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def has_attr(self, name):
        return name in self.attrs


class _EagerBlock:
    """Minimal Block stand-in so LoweringContext works outside a Program."""

    program = None

    def _find_var_recursive(self, name):
        return None


_EAGER_BLOCK = _EagerBlock()


def _is_float(v):
    return jnp.issubdtype(jnp.asarray(v).dtype, jnp.inexact)


class TapeNode:
    """One recorded op application; replayed through jax.vjp on backward.

    Role parity: reference imperative `OpBase` grad node + the per-op grad
    kernel; here backward = vjp of the re-run forward (XLA CSEs the
    recomputation when the surrounding step is jitted).
    """

    __slots__ = ("op_type", "fwd", "in_tensors", "out_tensors", "float_out_idx")

    def __init__(self, op_type, fwd, in_tensors, out_tensors, float_out_idx):
        self.op_type = op_type
        self.fwd = fwd  # fn(*diff_vals) -> tuple of ALL output values
        self.in_tensors = in_tensors  # differentiable input Tensors
        self.out_tensors = out_tensors  # produced Tensors (flat)
        self.float_out_idx = float_out_idx

    def release(self):
        self.fwd = None
        self.in_tensors = ()
        self.out_tensors = ()


def _record(op_type, fwd, diff_tensors, out_tensors):
    float_out_idx = [i for i, t in enumerate(out_tensors) if _is_float(t._value)]
    node = TapeNode(op_type, fwd, tuple(diff_tensors), tuple(out_tensors), float_out_idx)
    for i in float_out_idx:
        out_tensors[i].grad_node = node
        out_tensors[i].stop_gradient = False
    return node


def apply_jax(fn, *tensors, n_out: int = 1):
    """Run an arbitrary jax-traceable fn on Tensors with tape recording.

    The eager escape hatch for operations with no IR op (indexing, casts).
    """
    from ..framework.program import Variable

    if any(isinstance(t, Variable) for t in tensors):
        raise NotImplementedError(
            "this operation has no static-graph lowering yet; it only works "
            "in dygraph mode (got a graph Variable)")
    record = base.grad_enabled() and any(
        (not t.stop_gradient) and _is_float(t._value) for t in tensors
    )
    diff = [t for t in tensors if _is_float(t._value) and (not t.stop_gradient or record)]
    # partition: differentiable args are floats; others are captured consts
    diff_ids = {id(t) for t in diff}
    const_vals = {id(t): t._value for t in tensors if id(t) not in diff_ids}

    def fwd(*vals):
        it = iter(vals)
        args = [next(it) if id(t) in diff_ids else const_vals[id(t)] for t in tensors]
        out = fn(*args)
        return out if isinstance(out, tuple) else (out,)

    outs = fwd(*[t._value for t in diff])
    out_tensors = [Tensor(o, stop_gradient=True) for o in outs]
    if record and diff:
        _record(fn.__name__ if hasattr(fn, "__name__") else "apply_jax",
                fwd, diff, out_tensors)
    return out_tensors[0] if n_out == 1 and len(out_tensors) == 1 else out_tensors


def run_op(op_type: str, inputs: Dict[str, object], attrs: Optional[dict] = None,
           out_slots: Optional[Sequence[str]] = None,
           out_counts: Optional[Dict[str, int]] = None) -> Dict[str, object]:
    """Execute one IR op eagerly.  Returns {slot: Tensor | [Tensor]}.

    `inputs` values may be Tensor, list[Tensor], or None (optional slot).
    """
    from ..framework import unique_name

    rule = get_lowering(op_type)
    attrs = dict(attrs or {})
    if out_slots is None:
        out_slots = _OUT_SLOTS.get(op_type, ("Out",))

    in_names: Dict[str, List[str]] = {}
    const_env: Dict[str, object] = {}
    diff_tensors: List[Tensor] = []
    diff_names: List[str] = []

    record = base.grad_enabled()
    any_diff_input = False

    def add_input(slot, t, i):
        nonlocal any_diff_input
        name = f"__ein_{slot}_{i}_{id(t)}"
        in_names.setdefault(slot, []).append(name)
        if _is_float(t._value):
            if not t.stop_gradient:
                any_diff_input = True
            diff_tensors.append(t)
            diff_names.append(name)
        else:
            const_env[name] = t._value
        return name

    tensor_inputs: Dict[str, List[Tensor]] = {}
    for slot, v in inputs.items():
        if v is None:
            continue
        ts = v if isinstance(v, (list, tuple)) else [v]
        ts = [t if isinstance(t, Tensor) else Tensor(jnp.asarray(t)) for t in ts]
        tensor_inputs[slot] = ts
        for i, t in enumerate(ts):
            add_input(slot, t, i)

    # output slot sizing
    out_names: Dict[str, List[str]] = {}
    flat_out_names: List[str] = []
    for slot in out_slots:
        n = (out_counts or {}).get(slot, 1)
        names = [f"__eout_{slot}_{i}_{unique_name.generate('e')}" for i in range(n)]
        out_names[slot] = names
        flat_out_names.extend(names)

    op = _EagerOp(op_type, in_names, out_names, attrs)
    rng_key = base.next_eager_key()
    amp_dtype, amp_gray_dtype = _amp_policy(op_type)

    def fwd(*vals):
        env = dict(const_env)
        env.update(zip(diff_names, vals))
        cast_to = amp_dtype
        if amp_gray_dtype is not None:
            # gray-follow (mirrors static_amp's rewrite): once one input
            # is low precision, cast the fp32 rest down so promotion
            # cannot lift the chain back to fp32
            low = any(
                jnp.asarray(env[n]).dtype in (jnp.bfloat16, jnp.float16)
                for names in in_names.values() for n in names
                if env.get(n) is not None)
            if low:
                cast_to = amp_gray_dtype
        if cast_to is not None:
            for names in in_names.values():
                for n in names:
                    v = env.get(n)
                    if v is not None and jnp.issubdtype(
                            jnp.asarray(v).dtype, jnp.floating):
                        env[n] = jnp.asarray(v).astype(cast_to)
        ctx = LoweringContext(_EAGER_BLOCK, env, rng_key=rng_key)
        rule(ctx, op)
        return tuple(env.get(n) for n in flat_out_names)

    out_vals = fwd(*[t._value for t in diff_tensors])
    if out_vals and builtins_all(v is None for v in out_vals):
        raise RuntimeError(
            f"op {op_type!r} produced none of the requested output slots "
            f"{list(out_slots)}; the lowering writes different slot names")

    produced_idx = [i for i, v in enumerate(out_vals) if v is not None]
    out_tensors_flat: List[Optional[Tensor]] = [
        Tensor(out_vals[i], stop_gradient=True) if i in set(produced_idx) else None
        for i in range(len(out_vals))
    ]

    if record and any_diff_input and diff_tensors:
        produced = [t for t in out_tensors_flat if t is not None]
        if any(_is_float(t._value) for t in produced):
            # backward closure must return positionally-stable outputs
            def fwd_stable(*vals):
                vs = fwd(*vals)
                return tuple(vs[i] for i in produced_idx)

            _record(op_type, fwd_stable, diff_tensors, produced)

    # reassemble {slot: Tensor | [Tensor]}
    result: Dict[str, object] = {}
    k = 0
    for slot in out_slots:
        n = len(out_names[slot])
        ts = out_tensors_flat[k:k + n]
        k += n
        if op_type in _LIST_OUT_OPS and _LIST_OUT_OPS[op_type] == slot:
            result[slot] = [t for t in ts if t is not None]
        else:
            result[slot] = ts[0] if n == 1 else ts

    # dygraph->static trace (jit.TracedLayer): record this op into the
    # program being built (reference imperative/jit ProgramDescTracer);
    # _TRACE_REC is set by jit._trace_guard so the common non-traced
    # path pays one global check, no import machinery
    if _TRACE_REC is not None:
        _TRACE_REC.record(op_type, tensor_inputs, attrs, result, out_slots)
    return result


class Tracer:
    """API-parity shim over the global dygraph state (reference
    imperative::Tracer)."""

    @property
    def _has_grad(self):
        return base.grad_enabled()

    def trace_op(self, type, inputs, outputs, attrs=None):
        res = run_op(type, inputs, attrs,
                     out_slots=tuple(outputs.keys()) if outputs else None)
        for slot, t in res.items():
            if slot in outputs and isinstance(outputs[slot], Tensor) and t is not None:
                caller = outputs[slot]
                caller._set_raw(t._value)
                caller.grad_node = t.grad_node
                caller.stop_gradient = t.stop_gradient
                if t.grad_node is not None:
                    # the tape must reference the tensor the caller keeps,
                    # or backward() seeds a cotangent nobody looks up
                    node = t.grad_node
                    node.out_tensors = tuple(
                        caller if o is t else o for o in node.out_tensors)
                if _TRACE_REC is not None:
                    # the trace must follow the caller's tensor identity
                    _TRACE_REC.alias(t, caller)
        return res


_tracer = Tracer()


def tracer() -> Tracer:
    return _tracer
