"""Eager Tensor: a jax array with autograd metadata.

Role parity: reference paddle/fluid/imperative/layer.h `VarBase` /
variable_wrapper.h (value + grad slot + stop_gradient) and the
python-side monkey-patched methods (fluid/dygraph/varbase_patch_methods.py).
TPU-native: the payload is a `jax.Array` living on the default backend
(TPU chip when present); ops on it are the same lowering rules as the
static path, applied eagerly.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import unique_name
from . import base


class Tensor:
    def __init__(self, value, name: Optional[str] = None, stop_gradient: bool = True,
                 persistable: bool = False):
        self._value = value if isinstance(value, jax.Array) else jnp.asarray(value)
        self.name = name or unique_name.generate("eager_tmp")
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.grad: Optional[Tensor] = None
        self.grad_node = None  # TapeNode that produced this tensor (None = leaf)
        self.trainable = True

    # -- basic introspection ------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def is_leaf(self):
        return self.grad_node is None

    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        return self.numpy().item(*args)

    def __len__(self):
        return int(self._value.shape[0])

    def __repr__(self):
        g = ", stop_gradient=False" if not self.stop_gradient else ""
        return f"Tensor(shape={self.shape}, dtype={self.dtype}{g},\n{self._value})"

    def __bool__(self):
        return bool(self._value)

    def __float__(self):
        return float(self._value)

    def __int__(self):
        return int(self._value)

    def __hash__(self):
        return id(self)

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from .backward import run_backward

        seed = None if grad_tensor is None else grad_tensor._value
        run_backward([self], [seed], retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self):
        t = Tensor(self._value, stop_gradient=True)
        t.name = self.name
        return t

    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    # in-place value swap (optimizer updates, state dict loading)
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        self._value = jnp.asarray(value).astype(self._value.dtype)
        return self

    def _set_raw(self, value):
        self._value = value
        return self

    def block_until_ready(self):
        try:
            self._value.block_until_ready()
        except AttributeError:
            pass
        return self

    # -- jax interop --------------------------------------------------------
    def __jax_array__(self):
        return self._value

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    # -- op helpers (routed through the eager dispatcher) --------------------
    def _ew(self, other, op_type, reverse=False):
        from .eager import run_op

        if not isinstance(other, Tensor):
            other = Tensor(jnp.asarray(other, dtype=self.dtype), stop_gradient=True)
        x, y = (other, self) if reverse else (self, other)
        return run_op(op_type, {"X": x, "Y": y}, {"axis": -1})["Out"]

    def __add__(self, o):
        return self._ew(o, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._ew(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._ew(o, "elementwise_sub", reverse=True)

    def __mul__(self, o):
        return self._ew(o, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._ew(o, "elementwise_div")

    def __rtruediv__(self, o):
        return self._ew(o, "elementwise_div", reverse=True)

    def __pow__(self, o):
        return self._ew(o, "elementwise_pow")

    def __mod__(self, o):
        return self._ew(o, "elementwise_mod")

    def __floordiv__(self, o):
        return self._ew(o, "elementwise_floordiv")

    def __matmul__(self, o):
        from .eager import run_op

        return run_op("matmul_v2", {"X": self, "Y": o}, {})["Out"]

    def __neg__(self):
        from .eager import run_op

        return run_op("scale", {"X": self}, {"scale": -1.0, "bias": 0.0})["Out"]

    def __eq__(self, o):  # noqa: E721 - tensor semantics, like the reference
        return self._ew(o, "equal")

    def __ne__(self, o):
        return self._ew(o, "not_equal")

    def __lt__(self, o):
        return self._ew(o, "less_than")

    def __le__(self, o):
        return self._ew(o, "less_equal")

    def __gt__(self, o):
        return self._ew(o, "greater_than")

    def __ge__(self, o):
        return self._ew(o, "greater_equal")

    def __getitem__(self, idx):
        from .eager import apply_jax

        # jnp indexing CLAMPS out-of-range indices, but the python
        # sequence protocol (iteration, reversed, in) needs IndexError
        # to terminate — without it `for row in tensor` spins forever
        if isinstance(idx, (int, np.integer)):
            n = int(self._value.shape[0]) if self._value.ndim else 0
            if idx < -n or idx >= n:
                raise IndexError(
                    f"index {idx} out of range for dim 0 of size {n}")
        return apply_jax(lambda v: v[idx], self)

    def __iter__(self):
        """Iterate rows (reference VarBase iterates dim 0)."""
        if self._value.ndim == 0:
            raise TypeError("iteration over a 0-d tensor")
        return (self[i] for i in range(int(self._value.shape[0])))

    def register_hook(self, hook):
        """Gradient hook (reference imperative/hooks.h VarBase hooks):
        called with this tensor's gradient when backward computes it; a
        returned tensor/array REPLACES the gradient.  Returns a handle
        whose ``remove()`` detaches the hook."""
        if self.stop_gradient:
            raise RuntimeError(
                "cannot register a gradient hook on a tensor with "
                "stop_gradient=True")
        hooks = self.__dict__.setdefault("_grad_hooks", [])
        hooks.append(hook)

        class _Handle:
            def remove(_self):
                if hook in hooks:
                    hooks.remove(hook)

        return _Handle()

    def _apply_grad_hooks(self, g):
        """Run registered hooks over raw grad value ``g`` (jax array).
        Iterates a snapshot so a one-shot hook removing itself cannot
        skip its neighbor."""
        for h in tuple(self.__dict__.get("_grad_hooks", ())):
            out = h(Tensor(g))
            if out is not None:
                g = out._value if isinstance(out, Tensor) else \
                    jnp.asarray(out)
        return g

    # -- common methods -----------------------------------------------------
    def astype(self, dtype):
        from .eager import apply_jax
        from ..framework import dtypes

        jd = dtypes.to_jnp(dtype)
        return apply_jax(lambda v: v.astype(jd), self)

    cast = astype

    def reshape(self, shape):
        from .eager import run_op

        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = shape[0]
        return run_op("reshape2", {"X": self}, {"shape": list(shape)},
                      out_slots=("Out",))["Out"]

    def transpose(self, perm):
        from .eager import run_op

        return run_op("transpose2", {"X": self}, {"axis": list(perm)},
                      out_slots=("Out",))["Out"]

    def sum(self, axis=None, keepdim=False):
        from .eager import run_op

        attrs = {"dim": [] if axis is None else ([axis] if isinstance(axis, int) else list(axis)),
                 "keep_dim": keepdim, "reduce_all": axis is None}
        return run_op("reduce_sum", {"X": self}, attrs)["Out"]

    def mean(self, axis=None, keepdim=False):
        from .eager import run_op

        if axis is None and not keepdim:
            return run_op("mean", {"X": self}, {})["Out"]
        attrs = {"dim": [] if axis is None else ([axis] if isinstance(axis, int) else list(axis)),
                 "keep_dim": keepdim, "reduce_all": axis is None}
        return run_op("reduce_mean", {"X": self}, attrs)["Out"]

    def max(self, axis=None, keepdim=False):
        from .eager import run_op

        attrs = {"dim": [] if axis is None else ([axis] if isinstance(axis, int) else list(axis)),
                 "keep_dim": keepdim, "reduce_all": axis is None}
        return run_op("reduce_max", {"X": self}, attrs)["Out"]

    def min(self, axis=None, keepdim=False):
        from .eager import run_op

        attrs = {"dim": [] if axis is None else ([axis] if isinstance(axis, int) else list(axis)),
                 "keep_dim": keepdim, "reduce_all": axis is None}
        return run_op("reduce_min", {"X": self}, attrs)["Out"]

    def clone(self):
        from .eager import apply_jax

        return apply_jax(lambda v: v + 0, self)


class Parameter(Tensor):
    """Trainable eager tensor (reference framework.ParamBase)."""

    def __init__(self, value, name=None, trainable=True):
        super().__init__(value, name=name or unique_name.generate("param"),
                         stop_gradient=not trainable, persistable=True)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True

    def __repr__(self):
        return f"Parameter(name={self.name}, shape={self.shape}, dtype={self.dtype},\n{self._value})"
