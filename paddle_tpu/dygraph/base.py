"""Dygraph mode state: tracer switch, RNG, guard/no_grad contexts.

Role parity: reference paddle/fluid/imperative/tracer.{h,cc} (the global
tracer + `has_grad` switch) and python/paddle/fluid/dygraph/base.py
(`guard`, `no_grad`, `to_variable`).  TPU-native: eager execution IS jax
eager execution on the default backend; "tracing" here only records a
VJP-replay tape (see tape.py) — kernels are the same lowering rules the
static XLA path uses, so eager/static parity is by construction.
"""
from __future__ import annotations

import contextlib
import functools

import jax
import numpy as np


class _DygraphState:
    def __init__(self):
        self.mode_on = True  # reference defaults to dygraph in 2.0 API
        self.grad_enabled = True
        # lazy: creating a PRNGKey initialises the XLA backend, which
        # must not happen at import time (jax.distributed.initialize in
        # multi-process trainers must run first)
        self._rng_key = None

    @property
    def rng_key(self):
        if self._rng_key is None:
            self._rng_key = jax.random.PRNGKey(0)
        return self._rng_key

    @rng_key.setter
    def rng_key(self, value):
        self._rng_key = value


_state = _DygraphState()


def in_dygraph_mode() -> bool:
    return _state.mode_on


def enabled() -> bool:
    return _state.mode_on


def _switch_mode(on: bool):
    _state.mode_on = on


def enable_static():
    """Switch the 2.0 API into static-graph mode (reference
    paddle.enable_static)."""
    _state.mode_on = False


def disable_static():
    """Back to dygraph (reference paddle.disable_static)."""
    _state.mode_on = True


@contextlib.contextmanager
def guard(place=None):
    """Enter dygraph mode (reference dygraph/base.py `guard`)."""
    prev = _state.mode_on
    _state.mode_on = True
    try:
        yield
    finally:
        _state.mode_on = prev


def grad_enabled() -> bool:
    return _state.grad_enabled


class no_grad:
    """Context manager AND decorator disabling tape recording
    (reference dygraph/base.py `no_grad`).  Both ``@no_grad`` and
    ``@no_grad()`` work, as in the reference."""

    def __new__(cls, func=None):
        self = super().__new__(cls)
        if func is not None and callable(func):
            @functools.wraps(func)
            def wrapper(*args, **kwargs):
                with cls():
                    return func(*args, **kwargs)

            return wrapper
        return self

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper

    def __enter__(self):
        self._prev = _state.grad_enabled
        _state.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _state.grad_enabled = self._prev
        return False


@contextlib.contextmanager
def enable_grad():
    prev = _state.grad_enabled
    _state.grad_enabled = True
    try:
        yield
    finally:
        _state.grad_enabled = prev


def seed(value: int):
    """Seed BOTH execution modes (reference paddle.seed): the eager RNG key
    and the default programs' random_seed for static-graph runs."""
    _state.rng_key = jax.random.PRNGKey(int(value))
    from ..framework import program as prog_mod

    prog_mod.default_main_program().random_seed = int(value)
    prog_mod.default_startup_program().random_seed = int(value)


def next_eager_key():
    _state.rng_key, k = jax.random.split(_state.rng_key)
    return k


def to_variable(value, name=None, zero_copy=None, dtype=None):
    """numpy / scalar / Tensor -> eager Tensor (reference dygraph
    base.to_variable)."""
    from .tensor import Tensor

    if isinstance(value, Tensor):
        return value
    arr = np.asarray(value)
    if dtype is not None:
        arr = arr.astype(dtype)
    elif arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    # note: int64 collapses to int32 under jax's default x64-disabled mode
    return Tensor(jax.numpy.asarray(arr), name=name, stop_gradient=True)
