"""Declarative layer functions — build ops into the default main program.

Role parity: reference python/paddle/fluid/layers/ (nn.py 15.2k LoC,
tensor.py, loss.py).  Each function creates vars + one or more OpDescs;
execution happens when the Executor compiles the block to XLA.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .framework import dtypes
from .framework.program import Variable, default_main_program
from .initializer import ConstantInitializer, NormalInitializer
from .layer_helper import LayerHelper

__all__ = [
    "data",
    "fc",
    "conv2d",
    "pool2d",
    "batch_norm",
    "layer_norm",
    "embedding",
    "dropout",
    "relu",
    "sigmoid",
    "tanh",
    "gelu",
    "leaky_relu",
    "softmax",
    "log_softmax",
    "softmax_with_cross_entropy",
    "cross_entropy",
    "square_error_cost",
    "mean",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "accuracy",
    "topk",
    "argmax",
    "concat",
    "split",
    "reshape",
    "transpose",
    "flatten",
    "squeeze",
    "unsqueeze",
    "stack",
    "cast",
    "fill_constant",
    "assign",
    "zeros",
    "ones",
    "zeros_like",
    "ones_like",
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "mul",
    "matmul",
    "fused_multihead_attention",
    "moe_ffn",
    "scale",
    "clip",
    "clip_by_norm",
    "sqrt",
    "square",
    "abs",
    "exp",
    "log",
    "pow",
    "sum",
    "one_hot",
    "slice",
    "gather",
    "gather_nd",
    "scatter",
    "expand",
    "uniform_random",
    "gaussian_random",
    "dropout",
    "pad",
    "where",
    "equal",
    "not_equal",
    "less_than",
    "less_equal",
    "greater_than",
    "greater_equal",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "logical_and",
    "logical_not",
    "increment",
    "cumsum",
    "shape",
]


def _to_var(x, helper: LayerHelper, dtype="float32"):
    """Promote python scalars / numpy arrays to program vars."""
    if isinstance(x, Variable):
        return x
    arr = np.asarray(x)
    out = helper.create_variable_for_type_inference(str(arr.dtype), stop_gradient=True)
    out.shape = tuple(arr.shape)
    helper.append_op(
        "assign_value",
        {},
        {"Out": out},
        {
            "shape": list(arr.shape) or [1],
            "dtype": dtypes.to_enum(str(arr.dtype)),
            (
                "int32_values"
                if arr.dtype.kind == "i" and arr.dtype.itemsize <= 4
                else "int64_values"
                if arr.dtype.kind == "i"
                else "bool_values"
                if arr.dtype.kind == "b"
                else "fp32_values"
            ): arr.ravel().tolist(),
        },
    )
    return out


def _infer_unary_shape(x):
    return tuple(x.shape)


def _conv_hw(h, k, s, p, d=1):
    if h < 0:
        return -1
    return (h + 2 * p - (d * (k - 1) + 1)) // s + 1


def data(name, shape, dtype="float32", append_batch_size=True, lod_level=0):
    """Declare a feed slot (reference fluid.layers.data / fluid.data)."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = default_main_program().global_block
    var = block.create_var(
        name=name, shape=shape, dtype=dtype, stop_gradient=True
    )
    return var


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("fc", name=name)
    in_dim = 1
    for s in input.shape[num_flatten_dims:]:
        in_dim *= int(s)
    w = helper.create_parameter(param_attr, [in_dim, size], dtype=input.dtype_str)
    out = helper.create_variable_for_type_inference(input.dtype_str)
    out.shape = tuple(input.shape[:num_flatten_dims]) + (size,)
    helper.append_op(
        "mul",
        {"X": input, "Y": w},
        {"Out": out},
        {"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
    )
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [size], dtype=input.dtype_str, is_bias=True)
        out2 = helper.create_variable_for_type_inference(input.dtype_str)
        out2.shape = out.shape
        helper.append_op(
            "elementwise_add", {"X": out, "Y": b}, {"Out": out2}, {"axis": num_flatten_dims}
        )
        out = out2
    return helper.append_activation(out, act)


def moe_ffn(input, num_experts, ffn_dim=None, top_k=2,
            capacity_factor=1.25, param_attr=None, bias_attr=None,
            gate_attr=None, name=None):
    """Mixture-of-experts routed FFN (ops/moe_ops.py): top-k routing
    with capacity-factor dispatch over ``num_experts`` stacked expert
    FFNs.  Returns ``(out, aux_loss, expert_load)`` — add ``aux_loss``
    (Switch load-balance loss) into the training loss; ``expert_load``
    is the per-expert kept-token count gauge (stop-gradient)."""
    helper = LayerHelper("moe_ffn", name=name)
    d = int(input.shape[-1])
    h = int(ffn_dim or 4 * d)
    e = int(num_experts)
    gate_w = helper.create_parameter(
        gate_attr, [d, e], dtype=input.dtype_str,
        default_initializer=NormalInitializer(0.0, 0.02))
    w1 = helper.create_parameter(param_attr, [e, d, h],
                                 dtype=input.dtype_str)
    b1 = helper.create_parameter(bias_attr, [e, h],
                                 dtype=input.dtype_str, is_bias=True)
    w2 = helper.create_parameter(param_attr, [e, h, d],
                                 dtype=input.dtype_str)
    b2 = helper.create_parameter(bias_attr, [e, d],
                                 dtype=input.dtype_str, is_bias=True)
    out = helper.create_variable_for_type_inference(input.dtype_str)
    out.shape = tuple(input.shape)
    aux = helper.create_variable_for_type_inference("float32")
    aux.shape = (1,)
    load = helper.create_variable_for_type_inference(
        "float32", stop_gradient=True)
    load.shape = (e,)
    helper.append_op(
        "moe_ffn",
        {"X": input, "GateW": gate_w, "W1": w1, "B1": b1,
         "W2": w2, "B2": b2},
        {"Out": out, "AuxLoss": aux, "ExpertLoad": load},
        {"num_experts": e, "top_k": int(top_k),
         "capacity_factor": float(capacity_factor)},
    )
    return out, aux, load


def conv2d(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
    data_format="NCHW",
):
    helper = LayerHelper("conv2d", name=name)
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    stride = [stride, stride] if isinstance(stride, int) else list(stride)
    padding = [padding, padding] if isinstance(padding, int) else list(padding)
    dilation = [dilation, dilation] if isinstance(dilation, int) else list(dilation)
    c_in = int(input.shape[1] if data_format == "NCHW" else input.shape[-1])
    w_shape = [num_filters, c_in // groups] + list(filter_size)
    fan_in = (c_in // groups) * filter_size[0] * filter_size[1]
    w = helper.create_parameter(
        param_attr,
        w_shape,
        dtype=input.dtype_str,
        default_initializer=NormalInitializer(0.0, (2.0 / fan_in) ** 0.5),
    )
    out = helper.create_variable_for_type_inference(input.dtype_str)
    if len(input.shape) == 4:
        n, _, h, wd = (
            input.shape if data_format == "NCHW" else (input.shape[0], input.shape[3], input.shape[1], input.shape[2])
        )
        oh = _conv_hw(h, filter_size[0], stride[0], padding[0], dilation[0])
        ow = _conv_hw(wd, filter_size[1], stride[1], padding[1], dilation[1])
        out.shape = (n, num_filters, oh, ow) if data_format == "NCHW" else (n, oh, ow, num_filters)
    helper.append_op(
        "conv2d",
        {"Input": input, "Filter": w},
        {"Output": out},
        {
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "data_format": data_format,
        },
    )
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [num_filters], dtype=input.dtype_str, is_bias=True)
        out2 = helper.create_variable_for_type_inference(input.dtype_str)
        out2.shape = tuple(out.shape)
        helper.append_op(
            "elementwise_add",
            {"X": out, "Y": b},
            {"Out": out2},
            {"axis": 1 if data_format == "NCHW" else -1},
        )
        out = out2
    return helper.append_activation(out, act)


def pool2d(
    input,
    pool_size=-1,
    pool_type="max",
    pool_stride=1,
    pool_padding=0,
    global_pooling=False,
    ceil_mode=False,
    exclusive=True,
    name=None,
    data_format="NCHW",
):
    helper = LayerHelper("pool2d", name=name)
    pool_size = [pool_size] * 2 if isinstance(pool_size, int) else list(pool_size)
    pool_stride = [pool_stride] * 2 if isinstance(pool_stride, int) else list(pool_stride)
    pool_padding = [pool_padding] * 2 if isinstance(pool_padding, int) else list(pool_padding)
    out = helper.create_variable_for_type_inference(input.dtype_str)
    if len(input.shape) == 4:
        n, c, h, wd = (
            input.shape if data_format == "NCHW" else (input.shape[0], input.shape[3], input.shape[1], input.shape[2])
        )
        if global_pooling:
            oh = ow = 1
        else:
            oh = _conv_hw(h, pool_size[0], pool_stride[0], pool_padding[0])
            ow = _conv_hw(wd, pool_size[1], pool_stride[1], pool_padding[1])
        out.shape = (n, c, oh, ow) if data_format == "NCHW" else (n, oh, ow, c)
    helper.append_op(
        "pool2d",
        {"X": input},
        {"Out": out},
        {
            "pooling_type": pool_type,
            "ksize": pool_size,
            "strides": pool_stride,
            "paddings": pool_padding,
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
            "data_format": data_format,
        },
    )
    return out


def batch_norm(
    input,
    act=None,
    is_test=False,
    momentum=0.9,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    data_layout="NCHW",
    name=None,
    moving_mean_name=None,
    moving_variance_name=None,
    use_global_stats=False,
):
    helper = LayerHelper("batch_norm", name=name)
    c = int(input.shape[1] if data_layout == "NCHW" else input.shape[-1])
    scale = helper.create_parameter(
        param_attr, [c], dtype=input.dtype_str, default_initializer=ConstantInitializer(1.0)
    )
    bias = helper.create_parameter(bias_attr, [c], dtype=input.dtype_str, is_bias=True)
    mean = helper.create_global_variable(
        [c], dtype=input.dtype_str, name=moving_mean_name, initializer=ConstantInitializer(0.0)
    )
    variance = helper.create_global_variable(
        [c], dtype=input.dtype_str, name=moving_variance_name, initializer=ConstantInitializer(1.0)
    )
    out = helper.create_variable_for_type_inference(input.dtype_str)
    out.shape = tuple(input.shape)
    saved_mean = helper.create_variable_for_type_inference(input.dtype_str, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(input.dtype_str, stop_gradient=True)
    helper.append_op(
        "batch_norm",
        {"X": input, "Scale": scale, "Bias": bias, "Mean": mean, "Variance": variance},
        {
            "Y": out,
            "MeanOut": mean,
            "VarianceOut": variance,
            "SavedMean": saved_mean,
            "SavedVariance": saved_var,
        },
        {
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        },
    )
    return helper.append_activation(out, act)


def layer_norm(
    input,
    scale=True,
    shift=True,
    begin_norm_axis=1,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper("layer_norm", name=name)
    norm_dim = 1
    for s in input.shape[begin_norm_axis:]:
        norm_dim *= int(s)
    inputs = {"X": input}
    if scale:
        s_p = helper.create_parameter(
            param_attr, [norm_dim], dtype=input.dtype_str, default_initializer=ConstantInitializer(1.0)
        )
        inputs["Scale"] = s_p
    if shift:
        b_p = helper.create_parameter(bias_attr, [norm_dim], dtype=input.dtype_str, is_bias=True)
        inputs["Bias"] = b_p
    out = helper.create_variable_for_type_inference(input.dtype_str)
    out.shape = tuple(input.shape)
    mean = helper.create_variable_for_type_inference(input.dtype_str, stop_gradient=True)
    var = helper.create_variable_for_type_inference(input.dtype_str, stop_gradient=True)
    helper.append_op(
        "layer_norm",
        inputs,
        {"Y": out, "Mean": mean, "Variance": var},
        {"epsilon": epsilon, "begin_norm_axis": begin_norm_axis},
    )
    return helper.append_activation(out, act)


def embedding(
    input,
    size,
    is_sparse=False,
    padding_idx=None,
    param_attr=None,
    dtype="float32",
    name=None,
):
    helper = LayerHelper("embedding", name=name)
    w = helper.create_parameter(param_attr, list(size), dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = tuple(input.shape) + (int(size[1]),)
    helper.append_op(
        "lookup_table_v2",
        {"W": w, "Ids": input},
        {"Out": out},
        {"padding_idx": -1 if padding_idx is None else padding_idx,
         "is_sparse": bool(is_sparse)},
    )
    return out


def dropout(x, dropout_prob, is_test=False, seed=None, name=None, dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    out.shape = tuple(x.shape)
    mask = helper.create_variable_for_type_inference("uint8", stop_gradient=True)
    helper.append_op(
        "dropout",
        {"X": x},
        {"Out": out, "Mask": mask},
        {
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "seed": seed or 0,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


# ---------------------------------------------------------------------------
# simple op wrappers
# ---------------------------------------------------------------------------


def _unary(op_type):
    def f(x, name=None, **attrs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(x.dtype_str)
        out.shape = tuple(x.shape)
        helper.append_op(op_type, {"X": x}, {"Out": out}, attrs)
        return out

    f.__name__ = op_type
    return f


relu = _unary("relu")
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
gelu = _unary("gelu")
sqrt = _unary("sqrt")
square = _unary("square")
abs = _unary("abs")
exp = _unary("exp")
log = _unary("log")


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    helper.append_op("leaky_relu", {"X": x}, {"Out": out}, {"alpha": alpha})
    return out


def softmax(input, axis=-1, name=None):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype_str)
    out.shape = tuple(input.shape)
    helper.append_op("softmax", {"X": input}, {"Out": out}, {"axis": axis})
    return out


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper("log_softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype_str)
    helper.append_op("log_softmax", {"X": input}, {"Out": out}, {"axis": axis})
    return out


def softmax_with_cross_entropy(
    logits, label, soft_label=False, ignore_index=-100, axis=-1, return_softmax=False
):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(logits.dtype_str)
    loss = helper.create_variable_for_type_inference(logits.dtype_str)
    helper.append_op(
        "softmax_with_cross_entropy",
        {"Logits": logits, "Label": label},
        {"Softmax": softmax_out, "Loss": loss},
        {"soft_label": soft_label, "ignore_index": ignore_index, "axis": axis},
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype_str)
    helper.append_op(
        "cross_entropy",
        {"X": input, "Label": label},
        {"Y": out},
        {"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype_str)
    helper.append_op("square_error_cost", {"X": input, "Y": label}, {"Out": out})
    return out


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    out.shape = (1,)
    helper.append_op("mean", {"X": x}, {"Out": out})
    return out


def _reduce(op_type):
    def f(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(input.dtype_str)
        attrs = {"keep_dim": keep_dim, "reduce_all": dim is None}
        if dim is not None:
            attrs["dim"] = [dim] if isinstance(dim, int) else list(dim)
        helper.append_op(op_type, {"X": input}, {"Out": out}, attrs)
        return out

    return f


reduce_sum = _reduce("reduce_sum")
reduce_mean = _reduce("reduce_mean")
reduce_max = _reduce("reduce_max")
reduce_min = _reduce("reduce_min")


def topk(input, k=1, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype_str)
    indices = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    helper.append_op("top_k", {"X": input}, {"Out": values, "Indices": indices}, {"k": k})
    return values, indices


def argmax(x, axis=-1, name=None):
    helper = LayerHelper("arg_max", name=name)
    out = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    helper.append_op("arg_max", {"X": x}, {"Out": out}, {"axis": axis})
    return out


def accuracy(input, label, k=1, name=None):
    helper = LayerHelper("accuracy", name=name)
    values, indices = topk(input, k)
    acc = helper.create_variable_for_type_inference("float32", stop_gradient=True)
    correct = helper.create_variable_for_type_inference("int32", stop_gradient=True)
    total = helper.create_variable_for_type_inference("int32", stop_gradient=True)
    helper.append_op(
        "accuracy",
        {"Out": values, "Indices": indices, "Label": label},
        {"Accuracy": acc, "Correct": correct, "Total": total},
    )
    return acc


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype_str)
    helper.append_op("concat", {"X": input}, {"Out": out}, {"axis": axis})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        attrs = {"num": n, "axis": dim}
    else:
        n = len(num_or_sections)
        attrs = {"sections": list(num_or_sections), "axis": dim}
    outs = [helper.create_variable_for_type_inference(input.dtype_str) for _ in range(n)]
    helper.append_op("split", {"X": input}, {"Out": outs}, attrs)
    return outs


def reshape(x, shape, name=None, inplace=False, act=None):
    helper = LayerHelper("reshape2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    out.shape = tuple(
        int(x.shape[i]) if s == 0 and i < len(x.shape) else int(s)
        for i, s in enumerate(shape)
    )
    xshape = helper.create_variable_for_type_inference(x.dtype_str, stop_gradient=True)
    helper.append_op(
        "reshape2", {"X": x}, {"Out": out, "XShape": xshape}, {"shape": list(shape)}
    )
    return helper.append_activation(out, act)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    if x.shape and len(x.shape) == len(perm):
        out.shape = tuple(x.shape[p] for p in perm)
    xshape = helper.create_variable_for_type_inference(x.dtype_str, stop_gradient=True)
    helper.append_op(
        "transpose2", {"X": x}, {"Out": out, "XShape": xshape}, {"axis": list(perm)}
    )
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    xshape = helper.create_variable_for_type_inference(x.dtype_str, stop_gradient=True)
    helper.append_op("flatten2", {"X": x}, {"Out": out, "XShape": xshape}, {"axis": axis})
    return out


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype_str)
    xshape = helper.create_variable_for_type_inference(input.dtype_str, stop_gradient=True)
    helper.append_op("squeeze2", {"X": input}, {"Out": out, "XShape": xshape}, {"axes": list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype_str)
    xshape = helper.create_variable_for_type_inference(input.dtype_str, stop_gradient=True)
    helper.append_op("unsqueeze2", {"X": input}, {"Out": out, "XShape": xshape}, {"axes": list(axes)})
    return out


def stack(x, axis=0, name=None):
    helper = LayerHelper("stack", name=name)
    out = helper.create_variable_for_type_inference(x[0].dtype_str)
    helper.append_op("stack", {"X": x}, {"Y": out}, {"axis": axis})
    return out


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtypes.to_str(dtype))
    helper.append_op(
        "cast",
        {"X": x},
        {"Out": out},
        {"in_dtype": x.dtype, "out_dtype": dtypes.to_enum(dtype)},
    )
    return out


def fill_constant(shape, dtype, value, name=None, out=None):
    helper = LayerHelper("fill_constant", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(dtypes.to_str(dtype), stop_gradient=True)
        out.shape = tuple(shape)
    helper.append_op(
        "fill_constant",
        {},
        {"Out": out},
        {"shape": list(shape), "dtype": dtypes.to_enum(dtype), "value": float(value)},
    )
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, np.ndarray) or not isinstance(input, Variable):
        input = _to_var(input, helper)
    if output is None:
        output = helper.create_variable_for_type_inference(input.dtype_str)
    helper.append_op("assign", {"X": input}, {"Out": output})
    return output


def zeros(shape, dtype="float32"):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype="float32"):
    return fill_constant(shape, dtype, 1.0)


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype_str)
    helper.append_op("fill_any_like", {"X": x}, {"Out": out}, {"value": 0.0})
    return out


def ones_like(x, out=None):
    helper = LayerHelper("ones_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype_str)
    helper.append_op("fill_any_like", {"X": x}, {"Out": out}, {"value": 1.0})
    return out


def _binary(op_type):
    def f(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, name=name)
        if not isinstance(y, Variable):
            y = _to_var(y, helper)
        out = helper.create_variable_for_type_inference(x.dtype_str)
        out.shape = tuple(x.shape)
        helper.append_op(op_type, {"X": x, "Y": y}, {"Out": out}, {"axis": axis})
        return helper.append_activation(out, act)

    f.__name__ = op_type
    return f


elementwise_add = _binary("elementwise_add")
elementwise_sub = _binary("elementwise_sub")
elementwise_mul = _binary("elementwise_mul")
elementwise_div = _binary("elementwise_div")
elementwise_max = _binary("elementwise_max")
elementwise_min = _binary("elementwise_min")
elementwise_pow = _binary("elementwise_pow")


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    helper.append_op(
        "mul",
        {"X": x, "Y": y},
        {"Out": out},
        {"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    xs, ys = list(x.shape or ()), list(y.shape or ())
    if len(xs) >= 2 and len(ys) >= 2:
        m = xs[-1] if transpose_x else xs[-2]
        n = ys[-2] if transpose_y else ys[-1]
        xb, yb = xs[:-2], ys[:-2]
        # broadcast batch dims right-aligned (numpy semantics); dynamic
        # -1 dims survive unless the other operand pins a >1 extent
        # (then any valid runtime broadcast yields that extent)
        batch = []
        for i in range(max(len(xb), len(yb))):
            a = int(xb[-1 - i]) if i < len(xb) else 1
            c = int(yb[-1 - i]) if i < len(yb) else 1
            if a < 0 or c < 0:
                batch.append(max(a, c) if max(a, c) > 1 else -1)
            else:
                batch.append(max(a, c))
        batch.reverse()
        out.shape = tuple(batch) + (m, n)
    helper.append_op(
        "matmul",
        {"X": x, "Y": y},
        {"Out": out},
        {"transpose_X": transpose_x, "transpose_Y": transpose_y, "alpha": float(alpha)},
    )
    return out


def fused_multihead_attention(q, k, v, num_heads, bias_qk=None, alpha=0.0,
                              name=None):
    """Fused scaled-dot-product attention over [B, S, hidden] q/k/v
    (reference operators/fused/multihead_matmul_op.cu).  On TPU this is
    one Pallas flash kernel; ``alpha=0`` means 1/sqrt(head_dim)."""
    helper = LayerHelper("fused_multihead_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype_str)
    out.shape = tuple(q.shape)
    inputs = {"Q": q, "K": k, "V": v}
    if bias_qk is not None:
        inputs["BiasQK"] = bias_qk
    helper.append_op(
        "fused_multihead_attention", inputs, {"Out": out},
        {"head_number": num_heads, "alpha": float(alpha)})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    out.shape = tuple(x.shape)
    helper.append_op(
        "scale",
        {"X": x},
        {"Out": out},
        {"scale": float(scale), "bias": float(bias), "bias_after_scale": bias_after_scale},
    )
    return helper.append_activation(out, act)


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    helper.append_op("clip", {"X": x}, {"Out": out}, {"min": float(min), "max": float(max)})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    helper.append_op("clip_by_norm", {"X": x}, {"Out": out}, {"max_norm": float(max_norm)})
    return out


def pow(x, factor=1.0, name=None):
    helper = LayerHelper("pow", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    helper.append_op("pow", {"X": x}, {"Out": out}, {"factor": float(factor)})
    return out


def sum(x):
    helper = LayerHelper("sum")
    xs = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(xs[0].dtype_str)
    helper.append_op("sum", {"X": list(xs)}, {"Out": out})
    return out


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("one_hot_v2", {"X": input}, {"Out": out}, {"depth": depth})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(input.dtype_str)
    helper.append_op(
        "slice",
        {"Input": input},
        {"Out": out},
        {"axes": list(axes), "starts": list(starts), "ends": list(ends)},
    )
    return out


def gather(input, index, overwrite=True):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype_str)
    helper.append_op("gather", {"X": input, "Index": index}, {"Out": out})
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", name=name)
    out = helper.create_variable_for_type_inference(input.dtype_str)
    helper.append_op("gather_nd", {"X": input, "Index": index}, {"Out": out})
    return out


def scatter(input, index, updates, overwrite=True, name=None):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype_str)
    helper.append_op(
        "scatter",
        {"X": input, "Ids": index, "Updates": updates},
        {"Out": out},
        {"overwrite": overwrite},
    )
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    helper.append_op("expand", {"X": x}, {"Out": out}, {"expand_times": list(expand_times)})
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtypes.to_str(dtype), stop_gradient=True)
    helper.append_op(
        "uniform_random",
        {},
        {"Out": out},
        {"shape": list(shape), "dtype": dtypes.to_enum(dtype), "min": min, "max": max, "seed": seed},
    )
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtypes.to_str(dtype), stop_gradient=True)
    helper.append_op(
        "gaussian_random",
        {},
        {"Out": out},
        {"shape": list(shape), "dtype": dtypes.to_enum(dtype), "mean": mean, "std": std, "seed": seed},
    )
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    helper.append_op(
        "pad", {"X": x}, {"Out": out}, {"paddings": list(paddings), "pad_value": float(pad_value)}
    )
    return out


def where(condition, x, y, name=None):
    helper = LayerHelper("where", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    helper.append_op("where", {"Condition": condition, "X": x, "Y": y}, {"Out": out})
    return out


def _compare(op_type):
    def f(x, y, cond=None):
        helper = LayerHelper(op_type)
        if not isinstance(y, Variable):
            y = _to_var(y, helper)
        out = cond or helper.create_variable_for_type_inference("bool", stop_gradient=True)
        helper.append_op(op_type, {"X": x, "Y": y}, {"Out": out})
        return out

    return f


equal = _compare("equal")
not_equal = _compare("not_equal")
less_than = _compare("less_than")
less_equal = _compare("less_equal")
greater_than = _compare("greater_than")
greater_equal = _compare("greater_equal")


def logical_and(x, y, out=None, name=None):
    helper = LayerHelper("logical_and", name=name)
    out = out or helper.create_variable_for_type_inference("bool", stop_gradient=True)
    helper.append_op("logical_and", {"X": x, "Y": y}, {"Out": out})
    return out


def logical_not(x, out=None, name=None):
    helper = LayerHelper("logical_not", name=name)
    out = out or helper.create_variable_for_type_inference("bool", stop_gradient=True)
    helper.append_op("logical_not", {"X": x}, {"Out": out})
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype_str)
    helper.append_op("increment", {"X": x}, {"Out": out}, {"step": float(value)})
    return out


def cumsum(x, axis=None, name=None):
    helper = LayerHelper("cumsum", name=name)
    out = helper.create_variable_for_type_inference(x.dtype_str)
    attrs = {"flatten": axis is None}
    if axis is not None:
        attrs["axis"] = axis
    helper.append_op("cumsum", {"X": x}, {"Out": out}, attrs)
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference("int32", stop_gradient=True)
    helper.append_op("shape", {"Input": input}, {"Out": out})
    return out


# ---------------------------------------------------------------------------
# control flow (reference python/paddle/fluid/layers/control_flow.py —
# While:1020, while_loop:1035, cond:2333; ops lower to lax.while_loop /
# lax.cond, see ops/control_flow.py)
# ---------------------------------------------------------------------------


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """Functional while: loop_vars are updated in place by `body` until
    `cond` is false.  Carried state is exactly `loop_vars` (+ the
    condition), recorded on the op for the lax.while_loop lowering."""
    if not loop_vars:
        raise ValueError("while_loop requires at least one loop var")
    prog = default_main_program()
    parent = prog.current_block()

    pre_cond = cond(*loop_vars)
    if tuple(getattr(pre_cond, "shape", ())) not in ((), (1,)):
        raise TypeError(
            f"while_loop condition must be a scalar, got shape "
            f"{pre_cond.shape}")

    sub = prog._create_block()
    out_vars = body(*loop_vars)
    if not isinstance(out_vars, (list, tuple)):
        out_vars = [out_vars]
    if len(out_vars) != len(loop_vars):
        raise ValueError(
            f"body returned {len(out_vars)} vars, expected {len(loop_vars)}")
    for lv, ov in zip(loop_vars, out_vars):
        if ov.name != lv.name:
            assign(ov, lv)
    new_cond = cond(*loop_vars)
    if new_cond.name != pre_cond.name:
        assign(new_cond, pre_cond)
    prog._rollback()

    carried = [pre_cond.name] + [lv.name for lv in loop_vars]
    parent.append_op(
        "while",
        {"X": carried, "Condition": [pre_cond.name]},
        {"Out": list(carried)},
        {"sub_block": sub.idx, "is_test": is_test},
    )
    return loop_vars


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Two-branch conditional; both branches must produce matching
    structures (reference layers.cond:2333)."""
    helper = LayerHelper("cond", name=name)
    prog = default_main_program()
    parent = prog.current_block()

    def build(fn):
        sub = prog._create_block()
        out = fn() if fn is not None else None
        prog._rollback()
        if out is None:
            outs = []
        elif isinstance(out, (list, tuple)):
            outs = list(out)
        else:
            outs = [out]
        return sub, outs

    sub_t, t_outs = build(true_fn)
    sub_f, f_outs = build(false_fn)
    if len(t_outs) != len(f_outs):
        raise ValueError(
            f"cond branches return different numbers of outputs: "
            f"{len(t_outs)} vs {len(f_outs)}")
    results = []
    for tv in t_outs:
        out = helper.create_variable_for_type_inference(tv.dtype_str)
        out.shape = tuple(tv.shape)
        results.append(out)
    # record both branches' external reads as an input slot: the backward
    # (generic vjp over the re-emitted lax.cond) differentiates w.r.t.
    # these — params captured inside a branch get gradients
    captured = []
    for sub, outs in ((sub_t, t_outs), (sub_f, f_outs)):
        local = set()
        for op in sub.ops:
            for n in op.input_arg_names():
                if n not in local and n != pred.name and n not in captured:
                    captured.append(n)
            local.update(op.output_arg_names())
        # a branch may return a pre-existing parent var directly (no ops);
        # it is still an input of the cond
        for v in outs:
            if v.name not in local and v.name != pred.name \
                    and v.name not in captured:
                captured.append(v.name)
    parent.append_op(
        "cond_pair",
        {"Cond": [pred.name], "Captured": captured},
        {"Out": [r.name for r in results]},
        {"sub_block_t": sub_t.idx, "sub_block_f": sub_f.idx,
         "t_outs": [v.name for v in t_outs],
         "f_outs": [v.name for v in f_outs]},
    )
    if not results:
        return None
    return results[0] if len(results) == 1 else results


class While:
    """v1.8-style while context manager:

        i = layers.fill_constant([1], "int64", 0)
        c = layers.less_than(i, n)
        w = layers.While(c)
        with w.block():
            ... ops updating state ...
            layers.increment(i)
            layers.assign(layers.less_than(i, n), c)

    Carried state is inferred from the sub-block: the condition, every
    var read before written inside the loop, and every loop-written var
    that was already produced in the parent block."""

    def __init__(self, cond, is_test=False, name=None):
        if tuple(getattr(cond, "shape", ())) not in ((), (1,)):
            raise TypeError(
                f"While condition must be a scalar, got shape {cond.shape}")
        self.cond_var = cond
        self.is_test = is_test

    def block(self):
        import contextlib

        prog = default_main_program()
        parent = prog.current_block()
        parent_written = set()
        for op in parent.ops:
            parent_written.update(op.output_arg_names())
        this = self

        @contextlib.contextmanager
        def guard():
            sub = prog._create_block()
            try:
                yield
            finally:
                prog._rollback()
                read_before_write = []
                written = set()
                for op in sub.ops:
                    for n in op.input_arg_names():
                        if n not in written and n not in read_before_write:
                            read_before_write.append(n)
                    written.update(op.output_arg_names())
                carried = [this.cond_var.name]
                for n in sorted(written):
                    if n == this.cond_var.name:
                        continue
                    if n in read_before_write or n in parent_written:
                        carried.append(n)
                parent.append_op(
                    "while",
                    {"X": carried, "Condition": [this.cond_var.name]},
                    {"Out": list(carried)},
                    {"sub_block": sub.idx, "is_test": this.is_test},
                )

        return guard()
