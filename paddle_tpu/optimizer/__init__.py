"""`paddle.optimizer` equivalent (reference python/paddle/optimizer/).

2.0 optimizers work in BOTH modes: in dygraph `step()` runs the SAME
optimizer-op lowering rules eagerly over (param, param.grad); in static
graph `minimize()` delegates to the fluid-style program builders in
static_opt.py.  One numerical implementation per optimizer either way.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp

from .. import optimizer_lr as lr  # noqa: F401  (paddle.optimizer.lr.*)
from ..optimizer_lr import LRScheduler
from .static_opt import (  # noqa: F401  (fluid-compat re-exports)
    AdadeltaOptimizer,
    AdagradOptimizer,
    AdamaxOptimizer,
    AdamOptimizer,
    AdamWOptimizer,
    DpsgdOptimizer,
    ExponentialMovingAverage,
    FtrlOptimizer,
    LambOptimizer,
    LarsMomentumOptimizer,
    LookaheadOptimizer,
    ModelAverage,
    MomentumOptimizer,
    Optimizer as _FluidOptimizer,
    RMSPropOptimizer,
    SGDOptimizer,
)
from .pipeline_opt import PipelineOptimizer  # noqa: F401


class Optimizer:
    """2.0 optimizer base (reference python/paddle/optimizer/optimizer.py)."""

    _op_type: str = ""
    _fluid_cls = None

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **hyper):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._name = name
        self._hyper = hyper
        self._accum: Dict[int, Dict[str, object]] = {}
        self._fluid_opt = None

    # -- learning rate ----------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate.get_lr())
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)
        if self._fluid_opt is not None:
            self._fluid_opt.set_lr(value)

    # -- eager step -------------------------------------------------------
    def _accum_spec(self, p) -> Dict[str, tuple]:
        """name -> (shape_or_None_for_param_shape, fill_value)"""
        return {}

    def _io(self, p, g, lr_arr, acc):
        """Returns (inputs, attrs, out_slots, out_state_keys). Subclasses
        override; out_state_keys maps out slot -> accumulator name (or
        'param')."""
        raise NotImplementedError

    def _decayed_grad(self, p, g):
        wd = self._weight_decay
        if wd is None or isinstance(self, AdamW):
            return g
        coeff = getattr(wd, "_regularization_coeff", wd)
        try:
            coeff = float(coeff)
        except (TypeError, ValueError):
            return g
        if coeff == 0.0:
            return g
        return g + coeff * p._value

    def step(self):
        from ..dygraph import no_grad
        from ..dygraph.eager import run_op
        from ..dygraph.tensor import Tensor

        params = self._parameter_list or []
        params_grads = [(p, p.grad._value) for p in params
                        if p.grad is not None and p.trainable]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(
                [(p, g) for p, g in params_grads])
        lr_arr = jnp.asarray([self.get_lr()], dtype=jnp.float32)
        with no_grad():
            for p, g in params_grads:
                if g is None:
                    continue
                g = self._decayed_grad(p, g)
                acc = self._accum.setdefault(id(p), self._init_accum(p))
                inputs, attrs, out_slots, out_keys = self._io(p, g, lr_arr, acc)
                tin = {k: (Tensor(v) if not isinstance(v, Tensor) else v)
                       for k, v in inputs.items() if v is not None}
                res = run_op(self._op_type, tin, attrs, out_slots=out_slots)
                for slot, key in out_keys.items():
                    t = res.get(slot)
                    if t is None:
                        continue
                    if key == "param":
                        p._set_raw(t._value.astype(p._value.dtype))
                    else:
                        acc[key] = t._value

    def _init_accum(self, p):
        out = {}
        for name, (shape, fill) in self._accum_spec(p).items():
            shp = tuple(p.shape) if shape is None else tuple(shape)
            out[name] = jnp.full(shp, fill, dtype=jnp.float32)
        return out

    def clear_grad(self):
        for p in self._parameter_list or []:
            p.clear_grad()

    clear_gradients = clear_grad

    # -- static-mode delegation ------------------------------------------
    def _make_fluid(self):
        if self._fluid_opt is None:
            reg = None
            if self._weight_decay is not None and not isinstance(self, AdamW):
                from ..regularizer import L2Decay

                wd = self._weight_decay
                reg = wd if hasattr(wd, "__call__") or hasattr(
                    wd, "_regularization_coeff") else L2Decay(float(wd))
            self._fluid_opt = self._fluid_cls(
                learning_rate=self._learning_rate,
                regularization=reg, grad_clip=None,
                **self._fluid_kwargs())
        return self._fluid_opt

    def _fluid_kwargs(self):
        return dict(self._hyper)

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..framework.program import Variable

        if isinstance(loss, Variable):
            return self._make_fluid().minimize(loss, startup_program,
                                               parameters, no_grad_set)
        loss.backward()
        self.step()
        return None, None

    # -- state ------------------------------------------------------------
    def state_dict(self):
        sd = {"LR_Scheduler": (self._learning_rate.state_dict()
                               if isinstance(self._learning_rate, LRScheduler) else {})}
        for p in self._parameter_list or []:
            acc = self._accum.get(id(p))
            if acc:
                for name, v in acc.items():
                    sd[f"{p.name}_{name}"] = v
        return sd

    def set_state_dict(self, state):
        import numpy as np

        if isinstance(self._learning_rate, LRScheduler) and state.get("LR_Scheduler"):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])
        for p in self._parameter_list or []:
            acc = self._accum.setdefault(id(p), self._init_accum(p))
            for name in list(acc.keys()):
                key = f"{p.name}_{name}"
                if key in state:
                    acc[name] = jnp.asarray(np.asarray(state[key]))

    set_dict = set_state_dict


class SGD(Optimizer):
    _op_type = "sgd"
    _fluid_cls = SGDOptimizer

    def _io(self, p, g, lr, acc):
        return ({"Param": p, "Grad": g, "LearningRate": lr}, {},
                ("ParamOut",), {"ParamOut": "param"})


class Momentum(Optimizer):
    _op_type = "momentum"
    _fluid_cls = MomentumOptimizer

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         momentum=momentum, use_nesterov=use_nesterov)
        self._momentum, self._use_nesterov = momentum, use_nesterov

    def _accum_spec(self, p):
        return {"velocity": (None, 0.0)}

    def _io(self, p, g, lr, acc):
        return ({"Param": p, "Grad": g, "Velocity": acc["velocity"],
                 "LearningRate": lr},
                {"mu": self._momentum, "use_nesterov": self._use_nesterov},
                ("ParamOut", "VelocityOut"),
                {"ParamOut": "param", "VelocityOut": "velocity"})


class Adam(Optimizer):
    _op_type = "adam"
    _fluid_cls = AdamOptimizer

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None,
                 lazy_mode=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         beta1=beta1, beta2=beta2, epsilon=epsilon)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _accum_spec(self, p):
        return {"moment1": (None, 0.0), "moment2": (None, 0.0),
                "beta1_pow": ([1], self._beta1), "beta2_pow": ([1], self._beta2)}

    def _attrs(self, p):
        return {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon}

    def _io(self, p, g, lr, acc):
        return ({"Param": p, "Grad": g, "Moment1": acc["moment1"],
                 "Moment2": acc["moment2"], "Beta1Pow": acc["beta1_pow"],
                 "Beta2Pow": acc["beta2_pow"], "LearningRate": lr},
                self._attrs(p),
                ("ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut", "Beta2PowOut"),
                {"ParamOut": "param", "Moment1Out": "moment1",
                 "Moment2Out": "moment2", "Beta1PowOut": "beta1_pow",
                 "Beta2PowOut": "beta2_pow"})


class AdamW(Adam):
    _op_type = "adamw"
    _fluid_cls = AdamWOptimizer

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, grad_clip=None,
                 apply_decay_param_fun=None, lazy_mode=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, name)
        self._weight_decay = weight_decay if weight_decay is not None else 0.01
        self._apply_decay_param_fun = apply_decay_param_fun

    def _attrs(self, p):
        decay = float(self._weight_decay)
        if (self._apply_decay_param_fun is not None
                and not self._apply_decay_param_fun(p.name)):
            decay = 0.0
        return {"beta1": self._beta1, "beta2": self._beta2,
                "epsilon": self._epsilon, "coeff": decay,
                "with_decay": decay != 0.0}

    def _fluid_kwargs(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "epsilon": self._epsilon, "weight_decay": self._weight_decay,
                "apply_decay_param_fun": self._apply_decay_param_fun}


class Lamb(Adam):
    _op_type = "lamb"
    _fluid_cls = LambOptimizer

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, False, name)
        self._lamb_weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _attrs(self, p):
        wd = self._lamb_weight_decay
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        return {"beta1": self._beta1, "beta2": self._beta2,
                "epsilon": self._epsilon, "weight_decay": float(wd)}

    def _fluid_kwargs(self):
        return {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon}


class Adagrad(Optimizer):
    _op_type = "adagrad"
    _fluid_cls = AdagradOptimizer

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         epsilon=epsilon)
        self._epsilon = epsilon
        self._init_val = initial_accumulator_value

    def _accum_spec(self, p):
        return {"moment": (None, self._init_val)}

    def _io(self, p, g, lr, acc):
        return ({"Param": p, "Grad": g, "Moment": acc["moment"], "LearningRate": lr},
                {"epsilon": self._epsilon},
                ("ParamOut", "MomentOut"),
                {"ParamOut": "param", "MomentOut": "moment"})


class Adamax(Optimizer):
    _op_type = "adamax"
    _fluid_cls = AdamaxOptimizer

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         beta1=beta1, beta2=beta2, epsilon=epsilon)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _accum_spec(self, p):
        return {"moment": (None, 0.0), "inf_norm": (None, 0.0),
                "beta1_pow": ([1], self._beta1)}

    def _io(self, p, g, lr, acc):
        return ({"Param": p, "Grad": g, "Moment": acc["moment"],
                 "InfNorm": acc["inf_norm"], "Beta1Pow": acc["beta1_pow"],
                 "LearningRate": lr},
                {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
                ("ParamOut", "MomentOut", "InfNormOut"),
                {"ParamOut": "param", "MomentOut": "moment",
                 "InfNormOut": "inf_norm"})

    def step(self):
        super().step()
        # beta1_pow advances outside the op (reference _finish_update)
        for p in self._parameter_list or []:
            acc = self._accum.get(id(p))
            if acc and "beta1_pow" in acc:
                acc["beta1_pow"] = acc["beta1_pow"] * self._beta1


class RMSProp(Optimizer):
    _op_type = "rmsprop"
    _fluid_cls = RMSPropOptimizer

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         rho=rho, epsilon=epsilon, momentum=momentum, centered=centered)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _accum_spec(self, p):
        return {"mean_square": (None, 0.0), "mean_grad": (None, 0.0),
                "momentum": (None, 0.0)}

    def _io(self, p, g, lr, acc):
        return ({"Param": p, "Grad": g, "MeanSquare": acc["mean_square"],
                 "MeanGrad": acc["mean_grad"], "Moment": acc["momentum"],
                 "LearningRate": lr},
                {"decay": self._rho, "epsilon": self._epsilon,
                 "momentum": self._momentum, "centered": self._centered},
                ("ParamOut", "MeanSquareOut", "MeanGradOut", "MomentOut"),
                {"ParamOut": "param", "MeanSquareOut": "mean_square",
                 "MeanGradOut": "mean_grad", "MomentOut": "momentum"})
