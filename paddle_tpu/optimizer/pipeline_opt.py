"""PipelineOptimizer: fluid-style wrapper marking a program for the GPipe
executor.

Role parity: reference fluid.optimizer.PipelineOptimizer
(optimizer.py:3695) — wraps an inner optimizer, records the microbatch
count, and (in the reference) splits the program into per-device sections
run by PipelineTrainer.  Here the split happens at compile time
(distributed/pipeline.py analyze_stages over device_guard('stage:N')
annotations); minimize() just records the section boundaries the pipeline
executor needs: where the forward ends, where the backward ends, the loss,
and the param->grad map.
"""
from __future__ import annotations


class PipelineOptimizer:
    def __init__(self, optimizer, num_microbatches=1, start_cpu_core_id=0):
        if num_microbatches < 1:
            raise ValueError(f"num_microbatches must be >= 1, got "
                             f"{num_microbatches}")
        self._opt = optimizer
        self._num_microbatches = int(num_microbatches)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        prog = loss.block.program
        block = prog.global_block
        fwd_end = len(block.ops)
        params_grads = self._opt.backward(loss, startup_program,
                                          parameter_list, no_grad_set)
        bwd_end = len(block.ops)
        opt_ops = self._opt.apply_gradients(params_grads)
        prog._pipeline = {
            "fwd_end": fwd_end,
            "bwd_end": bwd_end,
            "num_microbatches": self._num_microbatches,
            "loss_name": loss.name,
            "params_grads": [
                (p.name, g.name if hasattr(g, "name") else g)
                for p, g in params_grads
            ],
        }
        prog._bump()
        return opt_ops, params_grads

    def __getattr__(self, name):
        if name == "_opt":
            raise AttributeError(name)
        return getattr(self._opt, name)
