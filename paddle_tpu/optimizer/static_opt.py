"""Optimizers — build update ops into the main program.

Role parity: reference python/paddle/fluid/optimizer.py (Optimizer base :57,
SGD :956, Momentum :1050, Adam :1853, Adamax :2119, Lamb :2962 ...) and
python/paddle/optimizer (AdamW).  ``minimize`` = append_backward +
regularization + grad clip + per-param update ops; the whole train step
(fwd+bwd+update) compiles to one XLA computation.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..framework import unique_name
from ..framework.backward import append_backward
from ..framework.program import (
    Program,
    Variable,
    default_main_program,
    default_startup_program,
)
from ..initializer import ConstantInitializer


class Optimizer:
    _accum_defaults: Dict[str, float] = {}

    def __init__(
        self,
        learning_rate=0.001,
        parameter_list=None,
        regularization=None,
        grad_clip=None,
        name=None,
    ):
        self._learning_rate = learning_rate
        self._parameter_list = parameter_list
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name or unique_name.generate(type(self).__name__)
        self._lr_var: Optional[Variable] = None
        self._accumulators: Dict[str, Dict[str, Variable]] = {}

    # -- learning rate ---------------------------------------------------
    def _create_global_learning_rate(self, program=None):
        if self._lr_var is not None:
            return self._lr_var
        from ..optimizer_lr import LRScheduler

        program = program or default_main_program()
        lr_value = self._learning_rate
        if isinstance(lr_value, LRScheduler):
            lr_value._bind(self)
            init = lr_value.get_lr()
        elif isinstance(lr_value, Variable):
            self._lr_var = lr_value
            return lr_value
        else:
            init = float(lr_value)
        name = unique_name.generate("learning_rate")
        self._lr_var = program.global_block.create_var(
            name=name, shape=[1], dtype="float32", persistable=True, stop_gradient=True
        )
        sb = default_startup_program().global_block
        sv = sb.create_var(name=name, shape=[1], dtype="float32", persistable=True)
        ConstantInitializer(init)(sv, sb)
        return self._lr_var

    def set_lr(self, value: float, scope=None):
        """Host-side LR update: writes the scalar into the scope (4-byte H2D,
        no recompile — the LR var is part of the compiled step's state)."""
        import numpy as np

        from ..framework.scope import global_scope

        scope = scope or global_scope()
        if self._lr_var is not None:
            scope.set_var(self._lr_var.name, np.asarray([value], dtype="float32"))

    def get_lr(self) -> float:
        import numpy as np

        from ..framework.scope import global_scope

        if self._lr_var is None:
            lr = self._learning_rate
            return float(lr if not hasattr(lr, "get_lr") else lr.get_lr())
        try:
            return float(np.asarray(global_scope().get_var(self._lr_var.name))[0])
        except KeyError:
            return 0.0

    # -- accumulators ----------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None, dtype="float32"):
        key = name
        self._accumulators.setdefault(key, {})
        if param.name in self._accumulators[key]:
            return self._accumulators[key][param.name]
        var_name = unique_name.generate(f"{param.name}_{name}")
        shape = list(shape if shape is not None else param.shape)
        v = default_main_program().global_block.create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True, stop_gradient=True
        )
        sb = default_startup_program().global_block
        sv = sb.create_var(name=var_name, shape=shape, dtype=dtype, persistable=True)
        ConstantInitializer(fill_value)(sv, sb)
        self._accumulators[key][param.name] = v
        return v

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- pipeline --------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        plist = parameter_list or self._parameter_list
        ckpts = getattr(loss.block.program, "_recompute_checkpoints", None)
        return append_backward(loss, parameter_list=plist,
                               no_grad_set=no_grad_set, checkpoints=ckpts)

    def apply_gradients(self, params_grads):
        params_grads = self._apply_regularization(params_grads)
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._create_global_learning_rate()
        block = default_main_program().global_block
        ops = []
        self._create_accumulators(block, [p for p, _ in params_grads])
        for p, g in params_grads:
            ops.append(self._append_optimize_op(block, (p, g)))
        self._finish_update(block, params_grads)
        return ops

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        params_grads = self.backward(
            loss, startup_program, parameter_list or self._parameter_list, no_grad_set
        )
        opt_ops = self.apply_gradients(params_grads)
        return opt_ops, params_grads

    # hooks
    def _create_accumulators(self, block, params):
        pass

    def _finish_update(self, block, params_grads):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _apply_regularization(self, params_grads):
        from ..regularizer import append_regularization_ops

        return append_regularization_ops(params_grads, self.regularization)

    # parity helper used by fleet / meta optimizers
    def _effective_lr_input(self, param):
        return self._lr_var


class SGDOptimizer(Optimizer):
    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "sgd",
            {"Param": p, "Grad": g, "LearningRate": self._lr_var},
            {"ParamOut": p},
        )


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            "momentum",
            {"Param": p, "Grad": g, "Velocity": v, "LearningRate": self._lr_var},
            {"ParamOut": p, "VelocityOut": v},
            {"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class _AdamBase(Optimizer):
    op_type = "adam"

    def __init__(
        self,
        learning_rate=0.001,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-8,
        lazy_mode=False,
        **kw,
    ):
        super().__init__(learning_rate, **kw)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow", p, fill_value=self._beta1, shape=[1])
            self._add_accumulator("beta2_pow", p, fill_value=self._beta2, shape=[1])

    def _extra_attrs(self, param):
        return {}

    def _append_optimize_op(self, block, pg):
        p, g = pg
        attrs = {
            "beta1": self._beta1,
            "beta2": self._beta2,
            "epsilon": self._epsilon,
            **self._extra_attrs(p),
        }
        return block.append_op(
            self.op_type,
            {
                "Param": p,
                "Grad": g,
                "Moment1": self._get_accumulator("moment1", p),
                "Moment2": self._get_accumulator("moment2", p),
                "Beta1Pow": self._get_accumulator("beta1_pow", p),
                "Beta2Pow": self._get_accumulator("beta2_pow", p),
                "LearningRate": self._lr_var,
            },
            {
                "ParamOut": p,
                "Moment1Out": self._get_accumulator("moment1", p),
                "Moment2Out": self._get_accumulator("moment2", p),
                "Beta1PowOut": self._get_accumulator("beta1_pow", p),
                "Beta2PowOut": self._get_accumulator("beta2_pow", p),
            },
            attrs,
        )


class AdamOptimizer(_AdamBase):
    op_type = "adam"


class AdamWOptimizer(_AdamBase):
    """Decoupled weight decay (paddle 2.0 paddle.optimizer.AdamW)."""

    op_type = "adamw"

    def __init__(self, learning_rate=0.001, weight_decay=0.01, apply_decay_param_fun=None, **kw):
        super().__init__(learning_rate, **kw)
        self._weight_decay = weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun

    def _extra_attrs(self, param):
        decay = self._weight_decay
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(param.name):
            decay = 0.0
        return {"coeff": float(decay), "with_decay": decay != 0.0}


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow", p, fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "adamax",
            {
                "Param": p,
                "Grad": g,
                "Moment": self._get_accumulator("moment", p),
                "InfNorm": self._get_accumulator("inf_norm", p),
                "Beta1Pow": self._get_accumulator("beta1_pow", p),
                "LearningRate": self._lr_var,
            },
            {
                "ParamOut": p,
                "MomentOut": self._get_accumulator("moment", p),
                "InfNormOut": self._get_accumulator("inf_norm", p),
            },
            {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )

    def _finish_update(self, block, params_grads):
        for p, _ in params_grads:
            b1p = self._get_accumulator("beta1_pow", p)
            block.append_op(
                "scale", {"X": b1p}, {"Out": b1p}, {"scale": self._beta1}
            )


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._init_accum = initial_accumulator_value

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment", p, fill_value=self._init_accum)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        return block.append_op(
            "adagrad",
            {"Param": p, "Grad": g, "Moment": m, "LearningRate": self._lr_var},
            {"ParamOut": p, "MomentOut": m},
            {"epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate=1.0, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "adadelta",
            {
                "Param": p,
                "Grad": g,
                "AvgSquaredGrad": self._get_accumulator("avg_squared_grad", p),
                "AvgSquaredUpdate": self._get_accumulator("avg_squared_update", p),
            },
            {
                "ParamOut": p,
                "AvgSquaredGradOut": self._get_accumulator("avg_squared_grad", p),
                "AvgSquaredUpdateOut": self._get_accumulator("avg_squared_update", p),
            },
            {"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("moment", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        inputs = {
            "Param": p,
            "Grad": g,
            "MeanSquare": self._get_accumulator("mean_square", p),
            "Moment": self._get_accumulator("moment", p),
            "LearningRate": self._lr_var,
        }
        outputs = {
            "ParamOut": p,
            "MeanSquareOut": self._get_accumulator("mean_square", p),
            "MomentOut": self._get_accumulator("moment", p),
        }
        if self._centered:
            inputs["MeanGrad"] = self._get_accumulator("mean_grad", p)
            outputs["MeanGradOut"] = self._get_accumulator("mean_grad", p)
        return block.append_op(
            "rmsprop",
            inputs,
            outputs,
            {
                "decay": self._rho,
                "epsilon": self._epsilon,
                "momentum": self._momentum,
                "centered": self._centered,
            },
        )


class LambOptimizer(_AdamBase):
    op_type = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999, epsilon=1e-6, exclude_from_weight_decay_fn=None, **kw):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2, epsilon=epsilon, **kw)
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _extra_attrs(self, param):
        wd = self._weight_decay
        if self._exclude_fn is not None and self._exclude_fn(param):
            wd = 0.0
        return {"weight_decay": float(wd)}


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001, lars_weight_decay=0.0005, epsilon=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            "lars_momentum",
            {"Param": p, "Grad": g, "Velocity": v, "LearningRate": self._lr_var},
            {"ParamOut": p, "VelocityOut": v},
            {
                "mu": self._momentum,
                "lars_coeff": self._lars_coeff,
                "lars_weight_decay": self._lars_weight_decay,
                "epsilon": self._epsilon,
            },
        )


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "ftrl",
            {
                "Param": p,
                "Grad": g,
                "SquaredAccumulator": self._get_accumulator("squared", p),
                "LinearAccumulator": self._get_accumulator("linear", p),
                "LearningRate": self._lr_var,
            },
            {
                "ParamOut": p,
                "SquaredAccumOut": self._get_accumulator("squared", p),
                "LinearAccumOut": self._get_accumulator("linear", p),
            },
            {"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


# reference spelling aliases
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
Adagrad = AdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
Ftrl = FtrlOptimizer
