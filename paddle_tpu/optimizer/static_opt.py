"""Optimizers — build update ops into the main program.

Role parity: reference python/paddle/fluid/optimizer.py (Optimizer base :57,
SGD :956, Momentum :1050, Adam :1853, Adamax :2119, Lamb :2962 ...) and
python/paddle/optimizer (AdamW).  ``minimize`` = append_backward +
regularization + grad clip + per-param update ops; the whole train step
(fwd+bwd+update) compiles to one XLA computation.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..framework import unique_name
from ..framework.backward import append_backward
from ..framework.program import (
    Program,
    Variable,
    default_main_program,
    default_startup_program,
)
from ..initializer import ConstantInitializer


class Optimizer:
    _accum_defaults: Dict[str, float] = {}

    def __init__(
        self,
        learning_rate=0.001,
        parameter_list=None,
        regularization=None,
        grad_clip=None,
        name=None,
    ):
        self._learning_rate = learning_rate
        self._parameter_list = parameter_list
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name or unique_name.generate(type(self).__name__)
        self._lr_var: Optional[Variable] = None
        self._accumulators: Dict[str, Dict[str, Variable]] = {}

    # -- learning rate ---------------------------------------------------
    def _create_global_learning_rate(self, program=None):
        if self._lr_var is not None:
            return self._lr_var
        from ..optimizer_lr import LRScheduler

        program = program or default_main_program()
        lr_value = self._learning_rate
        if isinstance(lr_value, LRScheduler):
            lr_value._bind(self)
            init = lr_value.get_lr()
        elif isinstance(lr_value, Variable):
            self._lr_var = lr_value
            return lr_value
        else:
            init = float(lr_value)
        name = unique_name.generate("learning_rate")
        self._lr_var = program.global_block.create_var(
            name=name, shape=[1], dtype="float32", persistable=True, stop_gradient=True
        )
        sb = default_startup_program().global_block
        sv = sb.create_var(name=name, shape=[1], dtype="float32", persistable=True)
        ConstantInitializer(init)(sv, sb)
        return self._lr_var

    def set_lr(self, value: float, scope=None):
        """Host-side LR update: writes the scalar into the scope (4-byte H2D,
        no recompile — the LR var is part of the compiled step's state)."""
        import numpy as np

        from ..framework.scope import global_scope

        scope = scope or global_scope()
        if self._lr_var is not None:
            scope.set_var(self._lr_var.name, np.asarray([value], dtype="float32"))

    def get_lr(self) -> float:
        import numpy as np

        from ..framework.scope import global_scope

        if self._lr_var is None:
            lr = self._learning_rate
            return float(lr if not hasattr(lr, "get_lr") else lr.get_lr())
        try:
            return float(np.asarray(global_scope().get_var(self._lr_var.name))[0])
        except KeyError:
            return 0.0

    # -- accumulators ----------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None, dtype="float32"):
        key = name
        self._accumulators.setdefault(key, {})
        if param.name in self._accumulators[key]:
            return self._accumulators[key][param.name]
        var_name = unique_name.generate(f"{param.name}_{name}")
        shape = list(shape if shape is not None else param.shape)
        v = default_main_program().global_block.create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True, stop_gradient=True
        )
        sb = default_startup_program().global_block
        sv = sb.create_var(name=var_name, shape=shape, dtype=dtype, persistable=True)
        ConstantInitializer(fill_value)(sv, sb)
        self._accumulators[key][param.name] = v
        return v

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- pipeline --------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        plist = parameter_list or self._parameter_list
        ckpts = getattr(loss.block.program, "_recompute_checkpoints", None)
        return append_backward(loss, parameter_list=plist,
                               no_grad_set=no_grad_set, checkpoints=ckpts)

    def apply_gradients(self, params_grads):
        params_grads = self._apply_regularization(params_grads)
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._create_global_learning_rate()
        block = default_main_program().global_block
        ops = []
        self._create_accumulators(block, [p for p, _ in params_grads])
        for p, g in params_grads:
            ops.append(self._append_optimize_op(block, (p, g)))
        self._finish_update(block, params_grads)
        return ops

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        params_grads = self.backward(
            loss, startup_program, parameter_list or self._parameter_list, no_grad_set
        )
        opt_ops = self.apply_gradients(params_grads)
        return opt_ops, params_grads

    # hooks
    def _create_accumulators(self, block, params):
        pass

    def _finish_update(self, block, params_grads):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _apply_regularization(self, params_grads):
        from ..regularizer import append_regularization_ops

        return append_regularization_ops(params_grads, self.regularization)

    # parity helper used by fleet / meta optimizers
    def _effective_lr_input(self, param):
        return self._lr_var


class SGDOptimizer(Optimizer):
    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "sgd",
            {"Param": p, "Grad": g, "LearningRate": self._lr_var},
            {"ParamOut": p},
        )


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            "momentum",
            {"Param": p, "Grad": g, "Velocity": v, "LearningRate": self._lr_var},
            {"ParamOut": p, "VelocityOut": v},
            {"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class _AdamBase(Optimizer):
    op_type = "adam"

    def __init__(
        self,
        learning_rate=0.001,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-8,
        lazy_mode=False,
        **kw,
    ):
        super().__init__(learning_rate, **kw)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow", p, fill_value=self._beta1, shape=[1])
            self._add_accumulator("beta2_pow", p, fill_value=self._beta2, shape=[1])

    def _extra_attrs(self, param):
        return {}

    def _append_optimize_op(self, block, pg):
        p, g = pg
        attrs = {
            "beta1": self._beta1,
            "beta2": self._beta2,
            "epsilon": self._epsilon,
            **self._extra_attrs(p),
        }
        return block.append_op(
            self.op_type,
            {
                "Param": p,
                "Grad": g,
                "Moment1": self._get_accumulator("moment1", p),
                "Moment2": self._get_accumulator("moment2", p),
                "Beta1Pow": self._get_accumulator("beta1_pow", p),
                "Beta2Pow": self._get_accumulator("beta2_pow", p),
                "LearningRate": self._lr_var,
            },
            {
                "ParamOut": p,
                "Moment1Out": self._get_accumulator("moment1", p),
                "Moment2Out": self._get_accumulator("moment2", p),
                "Beta1PowOut": self._get_accumulator("beta1_pow", p),
                "Beta2PowOut": self._get_accumulator("beta2_pow", p),
            },
            attrs,
        )


class AdamOptimizer(_AdamBase):
    op_type = "adam"


class AdamWOptimizer(_AdamBase):
    """Decoupled weight decay (paddle 2.0 paddle.optimizer.AdamW)."""

    op_type = "adamw"

    def __init__(self, learning_rate=0.001, weight_decay=0.01, apply_decay_param_fun=None, **kw):
        super().__init__(learning_rate, **kw)
        self._weight_decay = weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun

    def _extra_attrs(self, param):
        decay = self._weight_decay
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(param.name):
            decay = 0.0
        return {"coeff": float(decay), "with_decay": decay != 0.0}


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow", p, fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "adamax",
            {
                "Param": p,
                "Grad": g,
                "Moment": self._get_accumulator("moment", p),
                "InfNorm": self._get_accumulator("inf_norm", p),
                "Beta1Pow": self._get_accumulator("beta1_pow", p),
                "LearningRate": self._lr_var,
            },
            {
                "ParamOut": p,
                "MomentOut": self._get_accumulator("moment", p),
                "InfNormOut": self._get_accumulator("inf_norm", p),
            },
            {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )

    def _finish_update(self, block, params_grads):
        for p, _ in params_grads:
            b1p = self._get_accumulator("beta1_pow", p)
            block.append_op(
                "scale", {"X": b1p}, {"Out": b1p}, {"scale": self._beta1}
            )


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._init_accum = initial_accumulator_value

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("moment", p, fill_value=self._init_accum)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        return block.append_op(
            "adagrad",
            {"Param": p, "Grad": g, "Moment": m, "LearningRate": self._lr_var},
            {"ParamOut": p, "MomentOut": m},
            {"epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate=1.0, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "adadelta",
            {
                "Param": p,
                "Grad": g,
                "AvgSquaredGrad": self._get_accumulator("avg_squared_grad", p),
                "AvgSquaredUpdate": self._get_accumulator("avg_squared_update", p),
            },
            {
                "ParamOut": p,
                "AvgSquaredGradOut": self._get_accumulator("avg_squared_grad", p),
                "AvgSquaredUpdateOut": self._get_accumulator("avg_squared_update", p),
            },
            {"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("moment", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        inputs = {
            "Param": p,
            "Grad": g,
            "MeanSquare": self._get_accumulator("mean_square", p),
            "Moment": self._get_accumulator("moment", p),
            "LearningRate": self._lr_var,
        }
        outputs = {
            "ParamOut": p,
            "MeanSquareOut": self._get_accumulator("mean_square", p),
            "MomentOut": self._get_accumulator("moment", p),
        }
        if self._centered:
            inputs["MeanGrad"] = self._get_accumulator("mean_grad", p)
            outputs["MeanGradOut"] = self._get_accumulator("mean_grad", p)
        return block.append_op(
            "rmsprop",
            inputs,
            outputs,
            {
                "decay": self._rho,
                "epsilon": self._epsilon,
                "momentum": self._momentum,
                "centered": self._centered,
            },
        )


class LambOptimizer(_AdamBase):
    op_type = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999, epsilon=1e-6, exclude_from_weight_decay_fn=None, **kw):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2, epsilon=epsilon, **kw)
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _extra_attrs(self, param):
        wd = self._weight_decay
        if self._exclude_fn is not None and self._exclude_fn(param):
            wd = 0.0
        return {"weight_decay": float(wd)}


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001, lars_weight_decay=0.0005, epsilon=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            "lars_momentum",
            {"Param": p, "Grad": g, "Velocity": v, "LearningRate": self._lr_var},
            {"ParamOut": p, "VelocityOut": v},
            {
                "mu": self._momentum,
                "lars_coeff": self._lars_coeff,
                "lars_weight_decay": self._lars_weight_decay,
                "epsilon": self._epsilon,
            },
        )


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, params):
        for p in params:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "ftrl",
            {
                "Param": p,
                "Grad": g,
                "SquaredAccumulator": self._get_accumulator("squared", p),
                "LinearAccumulator": self._get_accumulator("linear", p),
                "LearningRate": self._lr_var,
            },
            {
                "ParamOut": p,
                "SquaredAccumOut": self._get_accumulator("squared", p),
                "LinearAccumOut": self._get_accumulator("linear", p),
            },
            {"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


# reference spelling aliases
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
Adagrad = AdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
Ftrl = FtrlOptimizer


def _make_persistent(block, startup, name, shape, value, init_from=None):
    """Persistable var in the main block + startup init (constant or
    copy-from another var).  Single definition for every accumulator
    these wrapper optimizers create."""
    v = block.create_var(name=name, shape=list(shape), dtype="float32",
                         persistable=True, stop_gradient=True)
    sv = startup.global_block.create_var(
        name=name, shape=list(shape), dtype="float32", persistable=True)
    if init_from is None:
        ConstantInitializer(value)(sv, startup.global_block)
    else:
        startup.global_block.append_op(
            "assign", {"X": [init_from]}, {"Out": [name]}, {})
    return v


class _ScopeSwap:
    """Shared apply()/restore() machinery for EMA / ModelAverage: swap
    computed values into the parameters, with backups held ON the
    instance so apply(need_restore=False) followed by a later
    restore() works (the reference pattern)."""

    def _swap_in(self, sc, values):
        self._backups = {}
        for pname, arr in values.items():
            import numpy as np

            self._backups[pname] = np.asarray(sc.get_var(pname)).copy()
            sc.set_var(pname, arr)
        self._backup_scope = sc

    def restore(self, executor=None, scope=None):
        from ..framework.scope import global_scope

        sc = scope or getattr(self, "_backup_scope", None) or global_scope()
        for pname, arr in (getattr(self, "_backups", None) or {}).items():
            sc.set_var(pname, arr)
        self._backups = {}

    def _guard(self, sc, values, need_restore):
        import contextlib

        @contextlib.contextmanager
        def guard():
            self._swap_in(sc, values)
            try:
                yield
            finally:
                if need_restore:
                    self.restore(scope=sc)

        return guard()


class DpsgdOptimizer(Optimizer):
    """Differentially-private SGD (reference optimizer.py Dpsgd +
    operators/optimizers/dpsgd_op.cc): clip + Gaussian noise on the
    batch gradient."""

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16.0,
                 sigma=1.0, **kw):
        super().__init__(learning_rate, **kw)
        self._clip = float(clip)
        self._batch_size = float(batch_size)
        self._sigma = float(sigma)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "dpsgd",
            {"Param": p, "Grad": g, "LearningRate": self._lr_var},
            {"ParamOut": p},
            {"clip": self._clip, "batch_size": self._batch_size,
             "sigma": self._sigma},
        )


class ExponentialMovingAverage(_ScopeSwap):
    """EMA of parameters (reference fluid.optimizer.
    ExponentialMovingAverage, optimizer.py:3443): ``update()`` appends
    shadow-accumulator ops to the current main program (run them every
    train step); ``apply(exe)`` swaps the bias-corrected shadow values
    into the parameters for evaluation (context manager, or
    need_restore=False + a later ``restore()``).  ``thres_steps`` turns
    on the reference's decay ramp min(decay, (1+t)/(10+t))."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._name = name or "ema"
        self._shadows = {}  # param name -> shadow var name
        self._step_name = None
        self._decay_hist = None  # prod of (per-step decay) for bias corr

    def update(self):
        from ..framework import unique_name
        from ..framework.program import (default_main_program,
                                         default_startup_program)

        main = default_main_program()
        startup = default_startup_program()
        block = main.global_block

        step = unique_name.generate(f"{self._name}_step")
        _make_persistent(block, startup, step, [1], 0.0)
        self._step_name = step
        block.append_op("increment", {"X": [step]}, {"Out": [step]},
                        {"step": 1.0})
        decay_inputs = {}
        if self._thres_steps is not None:
            # ramped decay: min(decay, (1+t)/(10+t)) — early steps lean
            # on recent weights instead of the near-zero shadow
            num = unique_name.generate(f"{self._name}_dnum")
            den = unique_name.generate(f"{self._name}_dden")
            ramp = unique_name.generate(f"{self._name}_ramp")
            for nm in (num, den, ramp):
                block.create_var(name=nm, shape=[1], dtype="float32",
                                 stop_gradient=True)
            block.append_op("scale", {"X": [step]}, {"Out": [num]},
                            {"scale": 1.0, "bias": 1.0,
                             "bias_after_scale": True})
            block.append_op("scale", {"X": [step]}, {"Out": [den]},
                            {"scale": 1.0, "bias": 10.0,
                             "bias_after_scale": True})
            block.append_op("elementwise_div",
                            {"X": [num], "Y": [den]}, {"Out": [ramp]},
                            {"axis": -1})
            block.append_op("clip", {"X": [ramp]}, {"Out": [ramp]},
                            {"min": 0.0, "max": self._decay})
            decay_inputs = {"Decay": [ramp]}
            # bias correction needs prod(decay_t): carry it as state
            hist = unique_name.generate(f"{self._name}_dhist")
            _make_persistent(block, startup, hist, [1], 1.0)
            block.append_op("elementwise_mul",
                            {"X": [hist], "Y": [ramp]}, {"Out": [hist]},
                            {"axis": -1})
            self._decay_hist = hist
        for p in main.all_parameters():
            shadow = unique_name.generate(f"{p.name}_{self._name}")
            _make_persistent(block, startup, shadow, p.shape, 0.0)
            block.append_op(
                "ema_update",
                {"Param": [p.name], "Shadow": [shadow], **decay_inputs},
                {"ShadowOut": [shadow]}, {"decay": self._decay})
            self._shadows[p.name] = shadow

    def apply(self, executor=None, need_restore=True, scope=None):
        """params <- shadow / (1 - prod(decay_t))  (bias corrected)."""
        import numpy as np

        from ..framework.scope import global_scope

        sc = scope or global_scope()
        if self._decay_hist is not None and sc.has_var(self._decay_hist):
            prod = float(np.asarray(sc.get_var(self._decay_hist))
                         .ravel()[0])
        else:
            t = float(np.asarray(sc.get_var(self._step_name)).ravel()[0]) \
                if self._step_name and sc.has_var(self._step_name) else 0.0
            prod = self._decay ** t if t > 0 else 0.0
        corr = max(1.0 - prod, 1e-12)
        values = {p: np.asarray(sc.get_var(s)) / corr
                  for p, s in self._shadows.items()}
        return self._guard(sc, values, need_restore)


class ModelAverage(_ScopeSwap):
    """Windowed average of parameters (reference fluid.optimizer.
    ModelAverage, optimizer.py:3134).  The reference bounds the window
    with a sum_1/sum_2/sum_3 rotation; here a TWO-buffer masked
    rotation keeps the averaging window within
    [max_average_window, 2*max_average_window] with one fewer buffer
    (no control flow — the rotation is a masked select, XLA-friendly):
    when the current buffer's count hits the window, it rolls into the
    old buffer and restarts."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, name=None):
        self._name = name or "model_avg"
        self._window = max(1, int(max_average_window))
        self._sums = {}       # param -> (sum_cur, sum_old)
        self._cnt_cur = None
        self._cnt_old = None
        self.update()

    def update(self):
        from ..framework import unique_name
        from ..framework.program import (default_main_program,
                                         default_startup_program)

        main = default_main_program()
        startup = default_startup_program()
        block = main.global_block

        def temp(name, shape=(1,)):
            block.create_var(name=name, shape=list(shape),
                             dtype="float32", stop_gradient=True)
            return name

        cnt = unique_name.generate(f"{self._name}_cnt")
        cnt_old = unique_name.generate(f"{self._name}_cnt_old")
        _make_persistent(block, startup, cnt, [1], 0.0)
        _make_persistent(block, startup, cnt_old, [1], 0.0)
        self._cnt_cur, self._cnt_old = cnt, cnt_old
        block.append_op("increment", {"X": [cnt]}, {"Out": [cnt]},
                        {"step": 1.0})
        # rotation mask: cnt == window
        w = temp(unique_name.generate(f"{self._name}_w"))
        block.append_op("fill_constant", {}, {"Out": [w]},
                        {"shape": [1], "dtype": "float32",
                         "value": float(self._window)})
        cond = unique_name.generate(f"{self._name}_cond")
        block.create_var(name=cond, shape=[1], dtype="bool",
                         stop_gradient=True)
        block.append_op("equal", {"X": [cnt], "Y": [w]}, {"Out": [cond]})
        mask = temp(unique_name.generate(f"{self._name}_mask"))
        block.append_op("cast", {"X": [cond]}, {"Out": [mask]},
                        {"out_dtype": "float32"})
        inv = temp(unique_name.generate(f"{self._name}_inv"))
        block.append_op("scale", {"X": [mask]}, {"Out": [inv]},
                        {"scale": -1.0, "bias": 1.0,
                         "bias_after_scale": True})

        def rotate(cur, old, shape=(1,)):
            # old' = (1-mask)*old + mask*cur ; cur' = (1-mask)*cur
            keep = temp(unique_name.generate(f"{self._name}_keep"),
                        shape=shape)
            roll = temp(unique_name.generate(f"{self._name}_roll"),
                        shape=shape)
            block.append_op("elementwise_mul", {"X": [old], "Y": [inv]},
                            {"Out": [keep]}, {"axis": -1})
            block.append_op("elementwise_mul", {"X": [cur], "Y": [mask]},
                            {"Out": [roll]}, {"axis": -1})
            block.append_op("elementwise_add", {"X": [keep], "Y": [roll]},
                            {"Out": [old]}, {"axis": -1})
            block.append_op("elementwise_mul", {"X": [cur], "Y": [inv]},
                            {"Out": [cur]}, {"axis": -1})

        for p in main.all_parameters():
            s = unique_name.generate(f"{p.name}_{self._name}_sum")
            s_old = unique_name.generate(f"{p.name}_{self._name}_sum_old")
            _make_persistent(block, startup, s, p.shape, 0.0)
            _make_persistent(block, startup, s_old, p.shape, 0.0)
            block.append_op("elementwise_add",
                            {"X": [s], "Y": [p.name]}, {"Out": [s]},
                            {"axis": -1})
            rotate(s, s_old, shape=p.shape)
            self._sums[p.name] = (s, s_old)
        rotate(cnt, cnt_old)

    def apply(self, executor=None, need_restore=True, scope=None):
        import numpy as np

        from ..framework.scope import global_scope

        sc = scope or global_scope()
        n = (float(np.asarray(sc.get_var(self._cnt_cur)).ravel()[0])
             + float(np.asarray(sc.get_var(self._cnt_old)).ravel()[0]))
        values = {}
        if n > 0:
            for pname, (s, s_old) in self._sums.items():
                values[pname] = (np.asarray(sc.get_var(s))
                                 + np.asarray(sc.get_var(s_old))) / n
        return self._guard(sc, values, need_restore)


class LookaheadOptimizer:
    """Lookahead wrapper (reference optimizer.py:4853): the inner
    optimizer updates the fast weights every step; every k steps the
    slow weights move toward the fast ones (slow += alpha*(fast-slow))
    and the fast weights reset to them.  Masked-update form (no
    control flow), like GradientMergeOptimizer."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha must be in [0, 1]")
        self.inner = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ..framework import unique_name
        from ..framework.program import default_startup_program

        ops, pgs = self.inner.minimize(loss, startup_program,
                                       parameter_list, no_grad_set)
        main = loss.block.program
        startup = startup_program or default_startup_program()
        block = main.global_block

        def persistent(name, shape, value, init_from=None):
            return _make_persistent(block, startup, name, shape, value,
                                    init_from=init_from)

        step = unique_name.generate("la_step")
        persistent(step, [1], 0.0)
        block.append_op("increment", {"X": [step]}, {"Out": [step]},
                        {"step": 1.0})
        k_const = unique_name.generate("la_k")
        block.append_op("fill_constant", {}, {"Out": [k_const]},
                        {"shape": [1], "dtype": "float32",
                         "value": float(self.k)})
        cond = unique_name.generate("la_cond")
        block.create_var(name=cond, shape=[1], dtype="bool",
                         stop_gradient=True)
        block.append_op("equal", {"X": [step], "Y": [k_const]},
                        {"Out": [cond]})
        mask = unique_name.generate("la_mask")
        block.create_var(name=mask, shape=[1], dtype="float32",
                         stop_gradient=True)
        block.append_op("cast", {"X": [cond]}, {"Out": [mask]},
                        {"out_dtype": "float32"})
        inv = unique_name.generate("la_inv")
        block.create_var(name=inv, shape=[1], dtype="float32",
                         stop_gradient=True)
        block.append_op("scale", {"X": [mask]}, {"Out": [inv]},
                        {"scale": -1.0, "bias": 1.0,
                         "bias_after_scale": True})
        block.append_op("elementwise_mul", {"X": [step], "Y": [inv]},
                        {"Out": [step]}, {"axis": -1})

        for p, _ in pgs:
            slow = unique_name.generate(p.name + "_la_slow")
            persistent(slow, p.shape, 0.0, init_from=p.name)
            # slow' = slow + mask*alpha*(fast - slow)
            diff = unique_name.generate(p.name + "_la_diff")
            block.create_var(name=diff, shape=list(p.shape),
                             dtype="float32", stop_gradient=True)
            block.append_op("elementwise_sub",
                            {"X": [p.name], "Y": [slow]}, {"Out": [diff]},
                            {"axis": -1})
            block.append_op("scale", {"X": [diff]}, {"Out": [diff]},
                            {"scale": self.alpha, "bias": 0.0,
                             "bias_after_scale": True})
            block.append_op("elementwise_mul",
                            {"X": [diff], "Y": [mask]}, {"Out": [diff]},
                            {"axis": -1})
            block.append_op("elementwise_add",
                            {"X": [slow], "Y": [diff]}, {"Out": [slow]},
                            {"axis": -1})
            # fast' = (1-mask)*fast + mask*slow'
            keep = unique_name.generate(p.name + "_la_keep")
            block.create_var(name=keep, shape=list(p.shape),
                             dtype="float32", stop_gradient=True)
            block.append_op("elementwise_mul",
                            {"X": [p.name], "Y": [inv]}, {"Out": [keep]},
                            {"axis": -1})
            upd = unique_name.generate(p.name + "_la_upd")
            block.create_var(name=upd, shape=list(p.shape),
                             dtype="float32", stop_gradient=True)
            block.append_op("elementwise_mul",
                            {"X": [slow], "Y": [mask]}, {"Out": [upd]},
                            {"axis": -1})
            block.append_op("elementwise_add",
                            {"X": [keep], "Y": [upd]}, {"Out": [p.name]},
                            {"axis": -1})
        main._bump()
        return ops, pgs


Dpsgd = DpsgdOptimizer
