"""Parameter initializers — append init ops to the startup program.

Role parity: reference python/paddle/fluid/initializer.py (Constant, Uniform,
Normal, TruncatedNormal, Xavier, MSRA, NumpyArrayInitializer).
"""
from __future__ import annotations

import math

import numpy as np

from .framework import dtypes


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    def eager_value(self, shape, dtype, key):
        """Produce the initial value directly (dygraph parameter creation) —
        the startup-program path collapsed to one jax call."""
        raise NotImplementedError(
            f"{type(self).__name__} has no eager-mode value rule")


class ConstantInitializer(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, var, block):
        block.append_op(
            "fill_constant",
            {},
            {"Out": var.name},
            {"shape": list(var.shape), "dtype": var.dtype, "value": float(self.value)},
        )


    def eager_value(self, shape, dtype, key):
        import jax.numpy as jnp

        return jnp.full(tuple(shape), self.value, dtype=dtypes.to_jnp(dtype))


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op(
            "uniform_random",
            {},
            {"Out": var.name},
            {
                "shape": list(var.shape),
                "dtype": var.dtype,
                "min": float(self.low),
                "max": float(self.high),
                "seed": self.seed,
            },
        )


    def eager_value(self, shape, dtype, key):
        import jax

        return jax.random.uniform(key, tuple(shape), dtypes.to_jnp(dtype),
                                  float(self.low), float(self.high))


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            "gaussian_random",
            {},
            {"Out": var.name},
            {
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
            },
        )


    def eager_value(self, shape, dtype, key):
        import jax

        return self.loc + self.scale * jax.random.normal(
            key, tuple(shape), dtypes.to_jnp(dtype))


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            "truncated_gaussian_random",
            {},
            {"Out": var.name},
            {
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
            },
        )


    def eager_value(self, shape, dtype, key):
        import jax

        return self.loc + self.scale * jax.random.truncated_normal(
            key, -2.0, 2.0, tuple(shape), dtypes.to_jnp(dtype))


def _shape_fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    rs = 1
    for s in shape[2:]:
        rs *= s
    return shape[1] * rs, shape[0] * rs


def _fans(var):
    return _shape_fans(var.shape)


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = _fans(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std, self.seed)(var, block)


    def eager_value(self, shape, dtype, key):
        fi, fo = _shape_fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            return UniformInitializer(-limit, limit, self.seed).eager_value(shape, dtype, key)
        std = math.sqrt(2.0 / (fi + fo))
        return NormalInitializer(0.0, std, self.seed).eager_value(shape, dtype, key)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fans(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fi)
            NormalInitializer(0.0, std, self.seed)(var, block)


    def eager_value(self, shape, dtype, key):
        fi, _ = _shape_fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            return UniformInitializer(-limit, limit, self.seed).eager_value(shape, dtype, key)
        std = math.sqrt(2.0 / fi)
        return NormalInitializer(0.0, std, self.seed).eager_value(shape, dtype, key)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value: np.ndarray):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        vals = self.value.ravel().tolist()
        key = {
            "float32": "fp32_values",
            "float64": "fp32_values",
            "int32": "int32_values",
            "int64": "int64_values",
            "bool": "bool_values",
        }.get(dtypes.to_str(var.dtype), "fp32_values")
        block.append_op(
            "assign_value",
            {},
            {"Out": var.name},
            {"shape": list(self.value.shape), "dtype": var.dtype, key: vals},
        )


    def eager_value(self, shape, dtype, key):
        import jax.numpy as jnp

        return jnp.asarray(self.value, dtype=dtypes.to_jnp(dtype)).reshape(tuple(shape))


# reference-compatible aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer


def _global_weight_initializer():
    return XavierInitializer()


def _global_bias_initializer():
    return ConstantInitializer(0.0)
