"""`paddle.io` equivalent: Dataset / Sampler / DataLoader.

Role parity: reference python/paddle/fluid/reader.py (`DataLoader`:147)
+ fluid/dataloader/ (dataloader_iter.py:262 single-process / :467
multi-process workers, batch_sampler.py, dataset.py).  TPU-native notes:
the loader feeds a host-side pipeline; batches should be padded to
static shapes (XLA recompiles per new shape) — `DataLoader` keeps the
reference's drop_last/shuffle/collate semantics and adds background
prefetch so host IO overlaps device compute (the reference's
buffered_reader double-buffering role).
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Iterable, List, Optional, Sequence

import numpy as np


class Dataset:
    """Map-style dataset (reference fluid/dataloader/dataset.py)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        arrays = [np.asarray(t) if not hasattr(t, "numpy") else t.numpy()
                  for t in tensors]
        n = len(arrays[0])
        assert all(len(a) == n for a in arrays), "tensors must share dim 0"
        self.tensors = arrays

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset, self.indices = dataset, list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    assert sum(lengths) == len(dataset)
    rng = np.random.RandomState(generator if isinstance(generator, int) else None)
    perm = rng.permutation(len(dataset))
    out, ofs = [], 0
    for ln in lengths:
        out.append(Subset(dataset, perm[ofs:ofs + ln].tolist()))
        ofs += ln
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)
        self.generator = generator

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.RandomState(
            self.generator if isinstance(self.generator, int) else None)
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class DistributedBatchSampler(Sampler):
    """Shards batches across ranks (reference
    fluid/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        from ..distributed import get_rank, get_world_size

        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.epoch = 0
        n = len(dataset)
        import math

        self.num_samples = int(math.ceil(n / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        indices = list(range(n))
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices += indices[: self.total_size - n]
        local = indices[self.local_rank::self.nranks]
        batch = []
        for i in local:
            batch.append(i)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        import math

        if self.drop_last:
            return self.num_samples // self.batch_size
        return int(math.ceil(self.num_samples / self.batch_size))


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        if sampler is None:
            sampler = (RandomSampler(dataset) if shuffle
                       else SequenceSampler(dataset))
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch: List):
    """Stack samples into batch arrays (reference
    fluid/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if hasattr(sample, "numpy"):
        return np.stack([np.asarray(b.numpy()) for b in batch])
    arr = np.asarray(batch)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


class _StageIterator:
    """Consumer half of one background pipeline stage: bounded queue,
    ``_END`` marker, exception propagation, stop-event abandonment, and
    the ``input_wait_seconds`` accounting.  ``_PrefetchIterator`` (host
    batch assembly) and ``DevicePrefetcher`` (H2D transfer) are this
    plus a producer thread running ``_stage_fill``."""

    _END = object()

    def __init__(self, queue_size, record_wait=True):
        self._q = queue.Queue(maxsize=queue_size)
        self._exc_box: list = []
        self._stop_evt = threading.Event()
        self._done = False
        # input_wait_seconds is the TRAINING loop's stall metric: only
        # the OUTERMOST stage records it (an inner stage's queue waits
        # are background-thread idle time, not consumer stalls)
        self._record_wait = record_wait

    def _start(self, target, args):
        # the fill function must NOT hold a strong ref to self: a running
        # thread would keep the iterator alive forever and __del__ (the
        # worker-reaping trigger on abandonment) would never fire
        self._thread = threading.Thread(target=target, args=args,
                                        daemon=True)
        self._thread.start()

    def close(self):
        """Release the fill thread (and through it any worker processes)
        when the consumer abandons the iterator mid-epoch."""
        self._stop_evt.set()

    __del__ = close

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            # the single _END marker was already consumed and the fill
            # thread has exited: a re-entered exhausted iterator must
            # keep raising StopIteration, not block on an empty queue
            raise StopIteration
        if self._record_wait:
            import time as _time

            from ..observe.histogram import stat_time

            t0 = _time.perf_counter()
            item = self._q.get()
            stat_time("input_wait_seconds", _time.perf_counter() - t0)
        else:
            item = self._q.get()
        if item is self._END:
            self._done = True
            if self._exc_box:
                raise self._exc_box[0]
            raise StopIteration
        return item


class _PrefetchIterator(_StageIterator):
    """Background-thread prefetch (the reference buffered_reader /
    multiprocess worker role; threads suffice because workers mostly wait
    on IO and numpy releases the GIL)."""

    def __init__(self, make_batches, num_workers, prefetch_factor=2,
                 record_wait=True):
        super().__init__(max(2, num_workers * prefetch_factor),
                         record_wait=record_wait)
        self._start(_prefetch_fill,
                    (make_batches, self._q, self._exc_box, self._stop_evt))


def _stage_fill(gen, q, exc_box, stop_evt, end_marker, transform=None):
    """The one background pipeline-stage body (_PrefetchIterator and
    DevicePrefetcher both run this): pull items from ``gen``, optionally
    ``transform`` each, block-put into the bounded queue with stop-event
    polling, surface exceptions through ``exc_box``.

    The ``end_marker`` must ALWAYS reach the consumer, even when the
    queue is still full of undrained batches (e.g. an epoch with fewer
    batches than the queue capacity finishes before the consumer takes
    its first item) — a dropped marker blocks ``__next__`` forever.
    Block-put with the same stop-event polling as normal batches; only
    an explicit close() abandons delivery."""
    try:
        for b in gen:
            if transform is not None:
                b = transform(b)
            placed = False
            while not stop_evt.is_set():
                try:
                    q.put(b, timeout=0.25)
                    placed = True
                    break
                except queue.Full:
                    continue
            if not placed:
                break
    except BaseException as e:  # surfaced on the consumer side
        exc_box.append(e)
    finally:
        # abandonment path: closing the generator runs its finally,
        # which shuts down any worker processes it spawned
        if hasattr(gen, "close"):
            gen.close()
        while True:
            try:
                q.put(end_marker, timeout=0.25)
                break
            except queue.Full:
                if stop_evt.is_set():
                    break


def _prefetch_fill(make_batches, q, exc_box, stop_evt):
    _stage_fill(make_batches(), q, exc_box, stop_evt,
                _PrefetchIterator._END)


from ..framework.scope import is_device_array as _is_device_array  # noqa: E402


def _device_put_batch(batch, sharding):
    """Transfer every array leaf of ``batch`` (nested tuples/lists/
    dicts) to device, returning ``(device_batch, bytes_transferred)``.
    ``sharding`` may be a single jax Sharding/device applied to every
    leaf, or a dict/sequence matching the batch structure for per-feed
    placement.  Leaves that are already device arrays pass through
    untouched when no explicit sharding is requested (clean fallback
    for loaders that already yield device data)."""
    import jax

    n_bytes = 0

    def put(x, sh):
        nonlocal n_bytes
        if isinstance(x, dict):
            shs = sh if isinstance(sh, dict) else {k: sh for k in x}
            return {k: put(v, shs.get(k)) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            if isinstance(sh, (list, tuple)) and len(sh) == len(x):
                out = [put(v, s) for v, s in zip(x, sh)]
            else:
                out = [put(v, sh) for v in x]
            return tuple(out) if isinstance(x, tuple) else out
        if _is_device_array(x) and sh is None:
            return x  # already placed; nothing to transfer
        arr = x if hasattr(x, "nbytes") else np.asarray(x)
        n_bytes += int(getattr(arr, "nbytes", 0))
        return jax.device_put(arr, sh)

    return put(batch, sharding), n_bytes


def _device_prefetch_fill(it, q, exc_box, stop_evt, sharding):
    """Background transfer stage: pull host batches, ``jax.device_put``
    them (H2D overlaps device compute instead of serializing inside the
    jitted step call), queue device batches.  The queue/END/abandonment
    protocol is _stage_fill's — only the per-item transform differs."""
    from ..monitor import stat_add, stat_set
    from ..observe import tracer as otrace

    def to_device(b):
        with otrace.span("h2d_prefetch"):
            b, n = _device_put_batch(b, sharding)
            otrace.set_span_args(bytes=n)
        stat_set("h2d_bytes_per_step", n)
        stat_add("h2d_bytes_total", n)
        return b

    _stage_fill(it, q, exc_box, stop_evt, DevicePrefetcher._END,
                transform=to_device)


class DevicePrefetcher(_StageIterator):
    """Device-side input prefetch: wraps any batch iterable and moves
    the next ``prefetch_factor`` batches onto device from a background
    thread (double buffering), so the H2D transfer overlaps the device's
    compute instead of serializing inside the Executor's jitted call.

    ``sharding`` places leaves onto the step's feed sharding (a jax
    Sharding/device, or a dict/sequence matching the batch structure);
    ``None`` uses jax's default device.  Batches whose leaves are
    already device arrays pass through untouched.  Exceptions from the
    source iterable (or the transfer) surface on the consumer's
    ``next()``.  ``input_wait_seconds`` (histogram) records how long the
    consumer blocked per batch; ``h2d_bytes_per_step`` (gauge) /
    ``h2d_bytes_total`` (counter) and the ``h2d_prefetch`` tracer span
    account the transfers."""

    def __init__(self, iterable, prefetch_factor: int = 2, sharding=None):
        super().__init__(max(int(prefetch_factor), 1))
        it = iter(iterable)
        if isinstance(it, _StageIterator):
            # this stage is now the outermost: the inner stage's queue
            # waits happen on OUR background thread and must not be
            # recorded as training-loop input stalls.  Checked on the
            # ITERATOR — wrapping a DataLoader directly builds its
            # _PrefetchIterator only at iter()
            it._record_wait = False
        self._start(_device_prefetch_fill,
                    (it, self._q, self._exc_box, self._stop_evt, sharding))


_ENV_PIN_LOCK = threading.Lock()  # guards the JAX_PLATFORMS pin in start


def _worker_loop(wid, n_workers, dataset, collate, init_fn, task_q,
                 result_q, parent_pid):
    """Worker-process body.  Module-level so the spawn start method can
    pickle it by reference (a closure can't be).  Polls the task queue
    with a short timeout and watches the parent's liveness: if the
    parent is SIGKILL'd (daemon=True doesn't cover that), getppid() is
    reparented and the worker exits instead of surviving as an orphan
    holding queue/file state."""
    import os
    import queue as _q
    import sys

    # Never touch the accelerator from a worker.  The env pin from the
    # parent covers normal jax installs; site hooks that force the
    # platform list post-import (overriding JAX_PLATFORMS) need the
    # live config pinned too — without this, any stray jax.devices()
    # in user dataset code would initialize the device backend from
    # every worker.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "jax" in sys.modules:
        try:
            sys.modules["jax"].config.update("jax_platforms", "cpu")
        except Exception:
            pass
    global _worker_info
    _worker_info = WorkerInfo(wid, n_workers, dataset)
    if init_fn is not None:
        init_fn(wid)

    def put_watching_parent(item):
        """Bounded-queue put that also watches parent liveness — a
        worker blocked in put() when the parent is SIGKILL'd must exit,
        not survive as an orphan."""
        while True:
            try:
                result_q.put(item, timeout=2.0)
                return True
            except _q.Full:
                if os.getppid() != parent_pid:
                    return False

    while True:
        try:
            task = task_q.get(timeout=2.0)
        except _q.Empty:
            if os.getppid() != parent_pid:
                return  # parent died; don't orphan
            continue
        if task is None:
            return
        bid, idxs = task
        try:
            batch = collate([dataset[i] for i in idxs])
            ok = put_watching_parent((bid, batch, None))
        except BaseException:  # surfaced in the parent
            import traceback

            ok = put_watching_parent((bid, None, traceback.format_exc()))
        if not ok:
            return


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False,
                 drop_last=False, collate_fn=None, num_workers=0,
                 use_buffer_reader=True, prefetch_factor=2, use_shared_memory=True,
                 timeout=0, worker_init_fn=None, device_prefetch=False,
                 feed_sharding=None):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        # device-side input prefetch (DevicePrefetcher): batches come
        # back with array leaves already jax.device_put onto
        # ``feed_sharding`` from a background transfer thread
        self.device_prefetch = device_prefetch
        self.feed_sharding = feed_sharding
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def _batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk:
                    return
                if len(chunk) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(chunk)
        elif self.num_workers > 0:
            yield from self._worker_batches()
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def _worker_batches(self):
        """Real worker PROCESSES (reference dataloader_iter.py:467
        _DataLoaderIterMultiProcess): workers pull (batch_id, indices)
        tasks, run dataset[i] + collate, and send pickled batches back
        over queues; the parent reassembles in order with a bounded
        in-flight window.

        Workers are SPAWNED, not forked: the parent is a jax-initialized
        multithreaded process (fork from it deadlocks, and forked
        children would inherit live TPU client state — an orphan can
        keep the chip unavailable to every later process).  Spawned
        children start interpreter-fresh with JAX_PLATFORMS=cpu pinned
        so they can never touch the device; they run only dataset +
        collate (numpy), matching the reference's CPU-only worker
        contract.  Fork remains an explicit opt-in
        (PADDLE_TPU_WORKER_START=fork) for jax-free embedders; threads
        are the fallback when the dataset doesn't pickle."""
        import multiprocessing as mp
        import os

        start = os.environ.get("PADDLE_TPU_WORKER_START", "spawn")
        try:
            ctx = mp.get_context(start)
        except ValueError:
            yield from self._thread_batches()
            return

        if getattr(mp.current_process(), "_inheriting", False):
            # POSITIVE spawn-bootstrap check: we are a spawned child
            # still importing an UNGUARDED __main__ (a script that
            # iterates a num_workers>0 loader at module top level).
            # Fork tolerated such scripts; serve this child's copy of
            # the top-level loop on threads instead of tripping
            # python's bootstrap error.
            import warnings

            warnings.warn(
                "DataLoader: this process is a spawned worker re-running "
                "an unguarded script top level; serving its loader on "
                "threads.  Wrap the script's entry point in `if __name__ "
                "== '__main__':` to avoid re-executing top-level code "
                "once per worker.", RuntimeWarning, stacklevel=3)
            yield from self._thread_batches()
            return

        n_workers = self.num_workers
        task_q = ctx.Queue()
        # one window constant governs BOTH the result-queue capacity and
        # the dispatch in-flight bound — they must stay equal or workers
        # block on a queue smaller than the dispatch window
        max_in_flight = max(2, n_workers * self.prefetch_factor)
        result_q = ctx.Queue(maxsize=max_in_flight)

        procs = [ctx.Process(
            target=_worker_loop,
            args=(w, n_workers, self.dataset, self.collate_fn,
                  self.worker_init_fn, task_q, result_q, os.getpid()),
            daemon=True) for w in range(n_workers)]
        # spawned children must never initialize a TPU backend even if
        # something in their import chain touches jax — pin them to cpu
        # for the duration of the exec (env is captured at start()).
        # Import jax in the parent FIRST so its platform config is
        # already snapshotted and the temporary env pin cannot leak
        # into a concurrent first jax import on another thread.
        import jax  # noqa: F401

        import pickle

        started = False
        # the save/set/restore of the process-global env var must not
        # interleave across loaders iterating concurrently (train+eval),
        # or one thread's restore can leak the cpu pin permanently
        with _ENV_PIN_LOCK:
            saved_jp = os.environ.get("JAX_PLATFORMS")
            os.environ["JAX_PLATFORMS"] = "cpu"
            try:
                for p in procs:
                    p.start()
                started = True
            except BaseException as e:
                for p in procs:  # reap whatever partially started
                    if p.is_alive():
                        p.terminate()
                import warnings

                if isinstance(e, (pickle.PicklingError, TypeError,
                                  AttributeError)):
                    # spawn pickles (dataset, collate_fn,
                    # worker_init_fn) by value; closures / local
                    # classes don't pickle — degrade to the thread pool
                    # rather than erroring the epoch.  Loudly: threads
                    # are GIL-bound and skip worker_init_fn /
                    # get_worker_info semantics.
                    warnings.warn(
                        f"DataLoader: dataset/collate_fn/worker_init_fn "
                        f"not picklable for spawned workers ({e!r}); "
                        f"falling back to a thread pool (GIL-bound, no "
                        f"worker_init_fn / get_worker_info). Move the "
                        f"dataset class to module scope for real "
                        f"worker processes.", RuntimeWarning,
                        stacklevel=3)
                else:
                    # real errors (resource limits, …): propagate
                    # rather than silently changing the execution model
                    raise
            finally:
                if saved_jp is None:
                    os.environ.pop("JAX_PLATFORMS", None)
                else:
                    os.environ["JAX_PLATFORMS"] = saved_jp
        if not started:
            yield from self._thread_batches()
            return

        # timeout=0 (the default) means NO user deadline — block as long
        # as workers are alive (reference semantics); dead workers are
        # still detected on a liveness poll
        user_timeout = float(self.timeout) if self.timeout else None
        pending = {}  # bid -> batch, out-of-order arrivals
        next_out = 0
        dispatched = 0
        sampler_it = iter(self.batch_sampler)

        def recv():
            nonlocal next_out
            import queue as _q
            import time as _time

            # poll in <=10s slices even under a long user timeout so a
            # dead worker is diagnosed within seconds, not at deadline
            deadline = (_time.monotonic() + user_timeout) \
                if user_timeout else None
            while next_out not in pending:
                slice_t = 10.0 if deadline is None else max(
                    0.1, min(10.0, deadline - _time.monotonic()))
                try:
                    bid, batch, err = result_q.get(timeout=slice_t)
                except _q.Empty:
                    dead = [w for w, p in enumerate(procs)
                            if not p.is_alive()]
                    if dead:
                        raise RuntimeError(
                            f"DataLoader worker(s) {dead} died without "
                            f"producing their batch") from None
                    if deadline is not None and \
                            _time.monotonic() >= deadline:
                        raise RuntimeError(
                            f"DataLoader produced no batch within the "
                            f"configured timeout={user_timeout}s") from None
                    continue  # workers alive, deadline not hit: wait on
                if err is not None:
                    raise RuntimeError(
                        f"DataLoader worker failed on batch {bid}:\n{err}")
                pending[bid] = batch
            out = pending.pop(next_out)
            next_out += 1
            return out

        try:
            exhausted = False
            while True:
                while not exhausted and dispatched - next_out \
                        - len(pending) < max_in_flight:
                    try:
                        idxs = next(sampler_it)
                    except StopIteration:
                        exhausted = True
                        break
                    task_q.put((dispatched, list(idxs)))
                    dispatched += 1
                if next_out >= dispatched and exhausted:
                    return
                yield recv()
        finally:
            for _ in procs:
                task_q.put(None)
            for p in procs:
                p.join(timeout=5)
                if p.is_alive():
                    p.terminate()

    def _thread_batches(self):
        from concurrent.futures import ThreadPoolExecutor

        def load(idxs):
            return self.collate_fn([self.dataset[i] for i in idxs])

        in_flight = []
        max_in_flight = self.num_workers * self.prefetch_factor
        with ThreadPoolExecutor(self.num_workers) as pool:
            for idxs in self.batch_sampler:
                in_flight.append(pool.submit(load, idxs))
                while len(in_flight) >= max_in_flight:
                    yield in_flight.pop(0).result()
            for f in in_flight:
                yield f.result()

    def __iter__(self):
        if self.use_buffer_reader:
            it = _PrefetchIterator(self._batches, max(self.num_workers, 1),
                                   self.prefetch_factor,
                                   record_wait=not self.device_prefetch)
        else:
            it = self._batches()
        if self.device_prefetch:
            it = DevicePrefetcher(it, prefetch_factor=self.prefetch_factor,
                                  sharding=self.feed_sharding)
        return it

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("length of an IterableDataset loader is unknown")
        return len(self.batch_sampler)


class WorkerInfo:
    """Reference fluid.dataloader worker_info: visible only inside a
    worker process."""

    def __init__(self, wid, num_workers, dataset):
        self.id = wid
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    return _worker_info  # None in the main process


from .data_feed import MultiSlotDataFeed  # noqa: E402,F401
