"""`paddle.io` equivalent: Dataset / Sampler / DataLoader.

Role parity: reference python/paddle/fluid/reader.py (`DataLoader`:147)
+ fluid/dataloader/ (dataloader_iter.py:262 single-process / :467
multi-process workers, batch_sampler.py, dataset.py).  TPU-native notes:
the loader feeds a host-side pipeline; batches should be padded to
static shapes (XLA recompiles per new shape) — `DataLoader` keeps the
reference's drop_last/shuffle/collate semantics and adds background
prefetch so host IO overlaps device compute (the reference's
buffered_reader double-buffering role).
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Iterable, List, Optional, Sequence

import numpy as np


class Dataset:
    """Map-style dataset (reference fluid/dataloader/dataset.py)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        arrays = [np.asarray(t) if not hasattr(t, "numpy") else t.numpy()
                  for t in tensors]
        n = len(arrays[0])
        assert all(len(a) == n for a in arrays), "tensors must share dim 0"
        self.tensors = arrays

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset, self.indices = dataset, list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    assert sum(lengths) == len(dataset)
    rng = np.random.RandomState(generator if isinstance(generator, int) else None)
    perm = rng.permutation(len(dataset))
    out, ofs = [], 0
    for ln in lengths:
        out.append(Subset(dataset, perm[ofs:ofs + ln].tolist()))
        ofs += ln
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)
        self.generator = generator

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.RandomState(
            self.generator if isinstance(self.generator, int) else None)
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class DistributedBatchSampler(Sampler):
    """Shards batches across ranks (reference
    fluid/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        from ..distributed import get_rank, get_world_size

        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.epoch = 0
        n = len(dataset)
        import math

        self.num_samples = int(math.ceil(n / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        indices = list(range(n))
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices += indices[: self.total_size - n]
        local = indices[self.local_rank::self.nranks]
        batch = []
        for i in local:
            batch.append(i)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        import math

        if self.drop_last:
            return self.num_samples // self.batch_size
        return int(math.ceil(self.num_samples / self.batch_size))


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        if sampler is None:
            sampler = (RandomSampler(dataset) if shuffle
                       else SequenceSampler(dataset))
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch: List):
    """Stack samples into batch arrays (reference
    fluid/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if hasattr(sample, "numpy"):
        return np.stack([np.asarray(b.numpy()) for b in batch])
    arr = np.asarray(batch)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


class _PrefetchIterator:
    """Background-thread prefetch (the reference buffered_reader /
    multiprocess worker role; threads suffice because workers mostly wait
    on IO and numpy releases the GIL)."""

    _END = object()

    def __init__(self, make_batches, num_workers, prefetch_factor=2):
        self._q = queue.Queue(maxsize=max(2, num_workers * prefetch_factor))
        self._exc = None
        self._thread = threading.Thread(target=self._fill, args=(make_batches,),
                                        daemon=True)
        self._thread.start()

    def _fill(self, make_batches):
        try:
            for b in make_batches():
                self._q.put(b)
        except BaseException as e:  # surfaced on the consumer side
            self._exc = e
        finally:
            self._q.put(self._END)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._END:
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        return item


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False,
                 drop_last=False, collate_fn=None, num_workers=0,
                 use_buffer_reader=True, prefetch_factor=2, use_shared_memory=True,
                 timeout=0, worker_init_fn=None):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def _batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk:
                    return
                if len(chunk) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(chunk)
        elif self.num_workers > 0:
            # parallel sample fetch: a worker pool maps batches in order
            # with bounded in-flight batches (the reference's multiprocess
            # worker role; threads because loading is IO/numpy-bound)
            from concurrent.futures import ThreadPoolExecutor

            def load(idxs):
                return self.collate_fn([self.dataset[i] for i in idxs])

            in_flight = []
            max_in_flight = self.num_workers * self.prefetch_factor
            with ThreadPoolExecutor(self.num_workers) as pool:
                for idxs in self.batch_sampler:
                    in_flight.append(pool.submit(load, idxs))
                    while len(in_flight) >= max_in_flight:
                        yield in_flight.pop(0).result()
                for f in in_flight:
                    yield f.result()
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def __iter__(self):
        if self.use_buffer_reader:
            return _PrefetchIterator(self._batches, max(self.num_workers, 1),
                                     self.prefetch_factor)
        return self._batches()

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("length of an IterableDataset loader is unknown")
        return len(self.batch_sampler)


def get_worker_info():
    return None  # single-process host pipeline (workers are threads)


from .data_feed import MultiSlotDataFeed  # noqa: E402,F401
