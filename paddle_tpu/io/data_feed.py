"""MultiSlotDataFeed: file-sharded reader for MultiSlot text data.

Role parity: reference framework/data_feed.{h,cc} (MultiSlotDataFeed
:117) feeding PS-style trainers.  The parse hot loop is native C++
(paddle_tpu/native); this class shards files, batches instances, and
yields per-slot (values, lod) pairs — LoD level-0 semantics, dense
float slots reshaped to [batch, dim] when sequences are uniform.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from .. import native


class MultiSlotDataFeed:
    """``slots`` is a list of (name, type) or (name, type, dim) with type
    'f' (float values) or 'u' (uint64 ids), in the file's slot order.
    Declaring ``dim`` makes the slot DENSE: every instance must carry
    exactly ``dim`` values and batches come out as [batch, dim] arrays
    (deterministic shape); undeclared slots always yield flat values +
    lod offsets, even when a batch happens to be uniform."""

    def __init__(self, slots: Sequence[Tuple], batch_size: int):
        self.slots = [(s[0], s[1], s[2] if len(s) > 2 else None)
                      for s in slots]
        self.types = "".join(t for _, t, _ in self.slots)
        self.batch_size = int(batch_size)

    def parse(self, data: bytes):
        return native.parse_multislot(data, self.types)

    def read_file(self, path: str):
        with open(path, "rb") as f:
            n, parsed = self.parse(f.read())
        yield from self._batches(n, parsed)

    def read_files(self, paths: Sequence[str]):
        for p in paths:
            yield from self.read_file(p)

    def _batches(self, n: int, parsed):
        bs = self.batch_size
        # the final partial batch is yielded too (reference DataFeed
        # semantics: no silent data drop)
        for start in range(0, n, bs):
            cur = min(bs, n - start)
            batch: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
            for (name, _t, dim), (vals, lod) in zip(self.slots, parsed):
                lo, hi = lod[start], lod[start + cur]
                blod = lod[start:start + cur + 1] - lod[start]
                v = vals[lo:hi]
                if dim is not None:
                    widths = np.diff(blod)
                    if widths.size and not (widths == dim).all():
                        raise ValueError(
                            f"dense slot {name!r} declared dim {dim} but "
                            f"instances carry widths "
                            f"{sorted(set(widths.tolist()))}")
                    v = v.reshape(cur, int(dim))
                batch[name] = (v, blod)
            yield batch
