"""`paddle.utils` parity (reference python/paddle/utils/): small
developer helpers — unique_name, deprecated decorator, try_import,
and the download entry (which raises here: the TPU build runs in
zero-egress environments; point datasets at local files instead)."""
from __future__ import annotations

import functools
import importlib
import warnings

from ..framework import unique_name  # noqa: F401


def deprecated(update_to: str = "", since: str = "", reason: str = ""):
    """Reference utils/deprecated.py: warn once per call site, and make
    the warning VISIBLE (DeprecationWarning is filtered by default
    outside __main__ since py3.7 — the reference forces visibility for
    the same reason)."""

    def deco(fn):
        warned_sites = set()

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            import sys

            frame = sys._getframe(1)
            site = (frame.f_code.co_filename, frame.f_lineno)
            if site not in warned_sites:
                warned_sites.add(site)
                msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
                if since:
                    msg += f" since {since}"
                if update_to:
                    msg += f"; use {update_to} instead"
                if reason:
                    msg += f" ({reason})"
                with warnings.catch_warnings():
                    warnings.simplefilter("always", DeprecationWarning)
                    warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def try_import(module_name: str, err_msg: str = None):
    """Reference utils/lazy_import.py try_import."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"required optional module {module_name!r} is not "
                       f"installed") from e


def run_check():
    """Reference paddle.utils.run_check: verify the install can run a
    small program on the available device."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.framework.program import Program, program_guard

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("x", [4])
        y = layers.fc(x, 2)
    exe = pt.Executor(pt.framework.place._default_place())
    scope = pt.framework.Scope()
    exe.run(startup, scope=scope)
    out = exe.run(main, feed={"x": np.ones((2, 4), "float32")},
                  fetch_list=[y], scope=scope)
    if np.asarray(out[0]).shape != (2, 2):
        raise RuntimeError(  # explicit: survives python -O
            f"run_check produced shape {np.asarray(out[0]).shape}, "
            f"expected (2, 2) — the install is broken")
    print("paddle_tpu is installed successfully!")


def download(url, module_name=None, save_name=None, **kw):
    raise RuntimeError(
        "paddle_tpu.utils.download is unavailable: this build targets "
        "zero-egress TPU environments; place the file locally and point "
        "the dataset at it")
