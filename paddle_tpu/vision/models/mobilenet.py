"""MobileNetV1/V2 (reference python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py)."""
from ... import nn


class ConvBNLayer(nn.Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0, groups=1,
                 act="relu6"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=padding, groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = nn.ReLU6() if act == "relu6" else (
            nn.ReLU() if act == "relu" else None)

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act else x


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c1, out_c2, stride, scale=1.0):
        super().__init__()
        c1 = int(out_c1 * scale)
        c2 = int(out_c2 * scale)
        self.dw = ConvBNLayer(in_c, c1, 3, stride=stride, padding=1,
                              groups=in_c, act="relu")
        self.pw = ConvBNLayer(c1, c2, 1, act="relu")

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: int(c * scale)
        self.conv1 = ConvBNLayer(3, s(32), 3, stride=2, padding=1, act="relu")
        cfg = [(32, 32, 64, 1), (64, 64, 128, 2), (128, 128, 128, 1),
               (128, 128, 256, 2), (256, 256, 256, 1), (256, 256, 512, 2)] + \
              [(512, 512, 512, 1)] * 5 + \
              [(512, 512, 1024, 2), (1024, 1024, 1024, 1)]
        blocks = []
        for in_c, c1, c2, stride in cfg:
            blocks.append(DepthwiseSeparable(s(in_c), c1, c2, stride, scale))
        self.blocks = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten

            x = self.fc(flatten(x, 1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        hidden = int(round(in_c * expand_ratio))
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(in_c, hidden, 1))
        layers += [
            ConvBNLayer(hidden, hidden, 3, stride=stride, padding=1,
                        groups=hidden),
            ConvBNLayer(hidden, out_c, 1, act=None),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = int(32 * scale)
        features = [ConvBNLayer(3, in_c, 3, stride=2, padding=1)]
        for t, c, n, s in cfg:
            out_c = int(c * scale)
            for i in range(n):
                features.append(InvertedResidual(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        self.last_c = int(1280 * max(1.0, scale))
        features.append(ConvBNLayer(in_c, self.last_c, 1))
        self.features = nn.Sequential(*features)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(self.last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten

            x = self.classifier(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV2(scale=scale, **kwargs)
