"""Vision datasets (reference python/paddle/vision/datasets/).

Zero-egress build: no downloaders.  Each dataset loads from a local
`data_file`/`image_path` the user provides (same file formats as the
reference) and raises a clear error otherwise.  `FakeData` provides the
synthetic stand-in the test-suite and smoke benchmarks use.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io import Dataset


class FakeData(Dataset):
    """Deterministic synthetic image classification data."""

    def __init__(self, num_samples=512, image_shape=(1, 28, 28), num_classes=10,
                 transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        rs = np.random.RandomState(seed)
        self._protos = rs.randn(num_classes, *self.image_shape).astype("f4")
        self._seed = seed

    def __getitem__(self, idx):
        label = idx % self.num_classes
        rs = np.random.RandomState(self._seed + idx)
        img = self._protos[label] + 0.3 * rs.randn(*self.image_shape).astype("f4")
        if self.transform:
            img = self.transform(img)
        return img, np.asarray([label], dtype="int64")

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """MNIST from local idx/gz files (reference vision/datasets/mnist.py
    format; download is N/A in this zero-egress build)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download and not (image_path and label_path):
            raise RuntimeError(
                "MNIST download is unavailable in this zero-egress build; "
                "pass image_path=/label_path= pointing at local "
                "train-images-idx3-ubyte.gz / train-labels-idx1-ubyte.gz")
        if not image_path or not os.path.exists(image_path):
            raise FileNotFoundError(f"MNIST image file not found: {image_path}")
        self.transform = transform
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.astype("int64")

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32")[None, :, :] / 255.0
        if self.transform:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], dtype="int64")

    def __len__(self):
        return len(self.images)


class Cifar10(Dataset):
    """CIFAR-10 from a local python-version tar/pickle directory."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if not data_file or not os.path.exists(data_file):
            raise FileNotFoundError(
                "Cifar10 needs data_file= pointing at the local "
                "cifar-10-batches-py directory (no download in this build)")
        import pickle

        self.transform = transform
        batches = ([f"data_batch_{i}" for i in range(1, 6)]
                   if mode == "train" else ["test_batch"])
        xs, ys = [], []
        for b in batches:
            with open(os.path.join(data_file, b), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.extend(d[b"labels"])
        self.images = np.concatenate(xs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(ys, dtype="int64")

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32") / 255.0
        if self.transform:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], dtype="int64")

    def __len__(self):
        return len(self.images)


class DatasetFolder(Dataset):
    """Image-folder dataset (reference vision/datasets/folder.py); images
    are loaded with numpy (npy) or PIL when available."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        if not os.path.isdir(root):
            raise FileNotFoundError(root)
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        exts = extensions or (".npy", ".png", ".jpg", ".jpeg", ".bmp")
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(exts):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return img, np.asarray([label], dtype="int64")

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image

        return np.asarray(Image.open(path).convert("RGB"))
    except ImportError as e:
        raise RuntimeError(
            f"cannot load {path}: PIL is unavailable; use .npy files") from e
