"""`paddle.vision` equivalent (reference python/paddle/vision/)."""
from . import datasets, transforms  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from .datasets import Cifar10, DatasetFolder, FakeData, ImageFolder, MNIST  # noqa: F401
from .models import (  # noqa: F401
    LeNet,
    MobileNetV1,
    MobileNetV2,
    ResNet,
    VGG,
    mobilenet_v1,
    mobilenet_v2,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    vgg11,
    vgg13,
    vgg16,
    vgg19,
)
