"""Vision transforms (reference python/paddle/vision/transforms/).

Numpy-based host-side transforms (HWC uint8/float in, CHW float out via
ToTensor) — the data pipeline runs on host CPU, batches go to the chip.
"""
from __future__ import annotations

import numbers

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    """HWC [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype("float32") / 255.0
        else:
            arr = arr.astype("float32")
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        # scalars stay scalar so the channel count comes from the image
        self.mean = (float(mean) if isinstance(mean, numbers.Number)
                     else np.asarray(mean, dtype="float32"))
        self.std = (float(std) if isinstance(std, numbers.Number)
                    else np.asarray(std, dtype="float32"))
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, dtype="float32")
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        mean = (self.mean if isinstance(self.mean, float)
                else self.mean.reshape(shape))
        std = (self.std if isinstance(self.std, float)
               else self.std.reshape(shape))
        return (img - mean) / std


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.interpolation = interpolation

    def _apply_image(self, img):
        import jax

        arr = np.asarray(img, dtype="float32")
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[-1]
        if chw:
            out_shape = (arr.shape[0],) + self.size
        elif arr.ndim == 3:
            out_shape = self.size + (arr.shape[2],)
        else:
            out_shape = self.size
        method = {"bilinear": "bilinear", "nearest": "nearest",
                  "bicubic": "cubic"}[self.interpolation]
        return np.asarray(jax.image.resize(arr, out_shape, method=method))


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[-3:-1] if arr.ndim == 3 and arr.shape[-1] <= 4 else arr.shape[-2:]
        th, tw = self.size
        i, j = max((h - th) // 2, 0), max((w - tw) // 2, 0)
        if arr.ndim == 3 and arr.shape[-1] <= 4:  # HWC
            return arr[i:i + th, j:j + tw]
        if arr.ndim == 3:  # CHW
            return arr[:, i:i + th, j:j + tw]
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            arr = np.asarray(img)
            return arr[..., ::-1].copy() if arr.ndim == 3 and arr.shape[0] <= 4 \
                else arr[:, ::-1].copy() if arr.ndim == 2 else arr[:, ::-1, :].copy()
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc = arr.ndim == 2 or arr.shape[-1] <= 4
        if self.padding:
            p = self.padding
            pads = ((p, p), (p, p), (0, 0)) if (arr.ndim == 3 and hwc) else \
                   ((0, 0), (p, p), (p, p)) if arr.ndim == 3 else ((p, p), (p, p))
            arr = np.pad(arr, pads)
        h, w = (arr.shape[0], arr.shape[1]) if hwc else (arr.shape[1], arr.shape[2])
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        if arr.ndim == 2:
            return arr[i:i + th, j:j + tw]
        if hwc:
            return arr[i:i + th, j:j + tw]
        return arr[:, i:i + th, j:j + tw]


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW"):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)
