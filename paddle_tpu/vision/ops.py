"""``paddle.vision.ops`` functional namespace (reference
python/paddle/vision/ops.py): yolo_box, deform_conv2d, roi_align,
roi_pool over the op lowerings in ops/{detection,deformable,vision}_ops.
"""
from __future__ import annotations

from ..dispatch import op_call

__all__ = ["yolo_box", "deform_conv2d", "roi_align", "roi_pool"]


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    return op_call(
        "yolo_box", {"X": x, "ImgSize": img_size},
        {"anchors": [int(a) for a in anchors], "class_num": int(class_num),
         "conf_thresh": float(conf_thresh),
         "downsample_ratio": int(downsample_ratio),
         "clip_bbox": bool(clip_bbox), "scale_x_y": float(scale_x_y)},
        outs=("Boxes", "Scores"))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    def pair(v):
        return [v, v] if isinstance(v, int) else list(v)

    inputs = {"Input": x, "Offset": offset, "Filter": weight}
    op_type = "deformable_conv_v1"
    if mask is not None:
        inputs["Mask"] = mask
        op_type = "deformable_conv"
    out = op_call(
        op_type, inputs,
        {"strides": pair(stride), "paddings": pair(padding),
         "dilations": pair(dilation), "groups": int(groups),
         "deformable_groups": int(deformable_groups)},
        outs=("Output",))
    if bias is not None:
        from ..tensor.manipulation import reshape

        out = out + reshape(bias, [1, -1, 1, 1])
    return out


def _require_boxes_num(x, boxes_num, name):
    # the op-level fallback maps every roi to image 0 (fine for N==1);
    # for batched inputs that silent default would pool from the wrong
    # image — the reference requires boxes_num in dygraph, so do we
    if boxes_num is None and int(x.shape[0]) > 1:
        raise ValueError(
            f"{name} with a batched input (N={int(x.shape[0])}) requires "
            f"boxes_num to assign each roi to its image")


def roi_align(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    _require_boxes_num(x, boxes_num, "roi_align")
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    inputs = {"X": x, "ROIs": boxes}
    if boxes_num is not None:
        inputs["RoisNum"] = boxes_num
    return op_call(
        "roi_align", inputs,
        {"pooled_height": int(output_size[0]),
         "pooled_width": int(output_size[1]),
         "spatial_scale": float(spatial_scale),
         "sampling_ratio": int(sampling_ratio), "aligned": bool(aligned)},
        outs=("Out",))


def roi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
             name=None):
    _require_boxes_num(x, boxes_num, "roi_pool")
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    inputs = {"X": x, "ROIs": boxes}
    if boxes_num is not None:
        inputs["RoisNum"] = boxes_num
    out, _argmax = op_call(
        "roi_pool", inputs,
        {"pooled_height": int(output_size[0]),
         "pooled_width": int(output_size[1]),
         "spatial_scale": float(spatial_scale)},
        outs=("Out", "Argmax"))
    return out
