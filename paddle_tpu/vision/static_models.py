"""Static-graph (fluid-style) flagship model builders.

Role parity: the reference ships fluid ResNet/SE-ResNeXt/Transformer
builders as distributed-test workloads (e.g.
python/paddle/fluid/tests/unittests/dist_se_resnext.py,
dist_transformer.py) and benchmarks them via book-style programs.  These
builders produce the same networks as `paddle_tpu.vision.models` but as
ProgramDesc graphs for the compiled Executor path — the configuration the
BASELINE.json flagship benchmarks measure.
"""
from __future__ import annotations

from .. import layers


def _conv_bn(x, ch, k, stride=1, act=None, name=None):
    conv = layers.conv2d(
        x, ch, k, stride=stride, padding=(k - 1) // 2, bias_attr=False,
        name=None if name is None else name + "_conv")
    return layers.batch_norm(conv, act=act,
                             name=None if name is None else name + "_bn")


def _bottleneck(x, ch, stride, downsample, name):
    """ResNet v1.5 bottleneck: 1x1 -> 3x3(stride) -> 1x1(4*ch) + shortcut."""
    y = _conv_bn(x, ch, 1, act="relu", name=name + "_a")
    y = _conv_bn(y, ch, 3, stride=stride, act="relu", name=name + "_b")
    y = _conv_bn(y, ch * 4, 1, act=None, name=name + "_c")
    if downsample:
        x = _conv_bn(x, ch * 4, 1, stride=stride, act=None, name=name + "_ds")
    return layers.elementwise_add(x, y, act="relu")


def resnet(img, depth=50, class_num=1000):
    """ResNet-{50,101,152} trunk on an NCHW image variable -> logits."""
    cfg = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}[depth]
    chans = [64, 128, 256, 512]

    y = _conv_bn(img, 64, 7, stride=2, act="relu", name="res_conv1")
    y = layers.pool2d(y, 3, "max", 2, pool_padding=1)
    for stage, (n_blocks, ch) in enumerate(zip(cfg, chans)):
        for blk in range(n_blocks):
            stride = 2 if stage > 0 and blk == 0 else 1
            y = _bottleneck(y, ch, stride, downsample=(blk == 0),
                            name=f"res{stage + 2}{chr(97 + blk)}")
    y = layers.pool2d(y, global_pooling=True, pool_type="avg")
    logits = layers.fc(y, class_num, name="res_fc")
    return logits


def resnet50_train_program(batch_size=None, class_num=1000, lr=0.1,
                           momentum=0.9, img_shape=(3, 224, 224),
                           uint8_input=False):
    """Build (main, startup, feeds, loss) for a ResNet-50 training step.

    Matches BASELINE.json config 2/4 (ResNet-50 ImageNet, SGD+momentum).
    ``uint8_input`` moves image normalization ONTO the device: the feed
    is raw uint8 (4x less host->device bandwidth — the input-pipeline
    bench mode) and a cast+scale at the program head does the rest,
    fused into the first conv by XLA.
    """
    from ..framework.program import Program, program_guard
    from ..optimizer import MomentumOptimizer

    main, startup = Program(), Program()
    with program_guard(main, startup):
        if uint8_input:
            raw = layers.data("image", list(img_shape), dtype="uint8")
            img = layers.scale(layers.cast(raw, "float32"), 1.0 / 127.5,
                               bias=-1.0, bias_after_scale=True)
            img.shape = tuple(raw.shape)
        else:
            img = layers.data("image", list(img_shape))
        label = layers.data("label", [1], dtype="int64")
        logits = resnet(img, depth=50, class_num=class_num)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        opt = MomentumOptimizer(lr, momentum)
    return main, startup, (img, label), loss, opt
