"""API-stability gate: dump every public API signature to a stable text
form (reference tools/print_signatures.py + check_api_approvals.sh —
signature diffs require explicit approval).

Usage:
    python tools/print_signatures.py            # print to stdout
    python tools/print_signatures.py --check    # diff against API.spec
    python tools/print_signatures.py --update   # rewrite API.spec

CI contract (tests/test_tooling.py): the committed API.spec must match
the live package — any signature change must be made deliberately by
running --update in the same commit.
"""
from __future__ import annotations

import argparse
import hashlib
import inspect
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)
SPEC = os.path.join(ROOT, "API.spec")

MODULES = [
    "paddle_tpu",
    "paddle_tpu.nn",
    "paddle_tpu.nn.functional",
    "paddle_tpu.tensor",
    "paddle_tpu.optimizer",
    "paddle_tpu.static",
    "paddle_tpu.jit",
    "paddle_tpu.amp",
    "paddle_tpu.metric",
    "paddle_tpu.io",
    "paddle_tpu.distribution",
    "paddle_tpu.distributed",
    "paddle_tpu.distributed.embedding",
    "paddle_tpu.distributed.fleet",
    "paddle_tpu.distributed.fleet.elastic",
    "paddle_tpu.rec",
    "paddle_tpu.layers",
    "paddle_tpu.profiler",
    "paddle_tpu.text",
    "paddle_tpu.text.decode",
    "paddle_tpu.autograd",
    "paddle_tpu.slim",
    "paddle_tpu.monitor",
    "paddle_tpu.observe",
    "paddle_tpu.observe.flight",
    "paddle_tpu.observe.health",
    "paddle_tpu.observe.request_trace",
    "paddle_tpu.observe.slo",
    "paddle_tpu.observe.xla_stats",
    "paddle_tpu.ckpt",
    "paddle_tpu.framework.passes",
    "paddle_tpu.serving",
    "paddle_tpu.serving.decode",
    "paddle_tpu.serving.kv_cache",
    "paddle_tpu.utils",
    "paddle_tpu.nn.utils",
    "paddle_tpu.nn.initializer",
    "paddle_tpu.version",
]


def _sig(obj) -> str:
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"


def collect() -> list[str]:
    import importlib

    lines = []
    for modname in MODULES:
        mod = importlib.import_module(modname)
        public = getattr(mod, "__all__", None)
        if public is None:
            public = [n for n in dir(mod) if not n.startswith("_")]
        for name in sorted(public):
            obj = getattr(mod, name, None)
            if obj is None or inspect.ismodule(obj):
                continue
            qual = f"{modname}.{name}"
            if inspect.isclass(obj):
                lines.append(f"{qual} (class) __init__{_sig(obj.__init__)}")
                for m in sorted(vars(obj)):
                    if m.startswith("_"):
                        continue
                    attr = vars(obj)[m]
                    if inspect.isfunction(attr):
                        lines.append(f"{qual}.{m}{_sig(attr)}")
            elif callable(obj):
                lines.append(f"{qual}{_sig(obj)}")
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--update", action="store_true")
    args = ap.parse_args(argv)
    lines = collect()
    text = "\n".join(lines) + "\n"
    if args.update:
        with open(SPEC, "w") as f:
            f.write(text)
        print(f"wrote {len(lines)} signatures to {SPEC}")
        return 0
    if args.check:
        if not os.path.exists(SPEC):
            print("API.spec missing; run --update", file=sys.stderr)
            return 1
        with open(SPEC) as f:
            want = f.read()
        if want != text:
            import difflib

            diff = list(difflib.unified_diff(
                want.splitlines(), text.splitlines(),
                "API.spec", "live", lineterm=""))
            print("\n".join(diff[:80]), file=sys.stderr)
            print(f"\nAPI signatures changed ({len(diff)} diff lines); "
                  f"if intentional run: python tools/print_signatures.py "
                  f"--update", file=sys.stderr)
            return 1
        print(f"API.spec up to date "
              f"(md5 {hashlib.md5(text.encode()).hexdigest()})")
        return 0
    sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
