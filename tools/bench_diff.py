"""Compare two BENCH_*.json rounds and flag perf regressions.

The driver keeps one JSON per bench round (``BENCH_r01.json``..); until
now comparing rounds meant eyeballing. This CLI diffs any two:

    python -m tools.bench_diff BENCH_r03.json BENCH_r05.json
    python -m tools.bench_diff A.json B.json --threshold 0.10 --json

Input handling (pure stdlib, no framework import):

- Both the raw bench summary (what ``bench.py`` prints) and the
  driver's wrapper shape ``{"n", "cmd", "rc", "tail", "parsed"}`` are
  accepted — the wrapper is unwrapped to its ``parsed`` dict.
- Every numeric key present in both rounds is compared. Direction is
  inferred from the key name (throughput-like keys are
  higher-is-better, latency/size-like keys lower-is-better; unknown
  keys are reported as neutral and never flagged).
- **Honesty about broken rounds**: a round with ``rc != 0``, a
  ``status`` of ``partial``/``failed``/``recovered``, an ``error``
  field, or a zeroed ``vs_baseline`` did not produce trustworthy
  numbers. The diff still prints, but every flag is downgraded to
  *advisory* and the exit code stays 0 — a dead-device round must not
  read as a 100% regression.

Exit code: 1 only when both rounds are clean AND at least one metric
regressed past ``--threshold`` (default 5%).
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Dict, List, Optional, Tuple

__all__ = ["load_round", "classify", "diff_rounds", "main"]

# key-name → direction rules; first match wins, unknown keys neutral
_HIGHER = re.compile(
    r"(per_sec|_rps$|vs_baseline|speedup|goodput|accept|hit_rate|"
    r"fraction_of_synthetic|ratio$|_mfu|tokens_total|improvement|"
    r"bitwise_ok|reroles|balance)")
_LOWER = re.compile(
    r"(_seconds|_ms$|_s$|_p50|_p90|_p95|_p99|_bytes|bubble|pad_waste|"
    r"exposed|latency|restarts|_errors|dropped|redispatch|"
    r"parity_vs_oracle)")

_BAD_STATUS = ("partial", "failed", "recovered")


def load_round(path: str) -> Tuple[Dict, List[str]]:
    """(metrics dict, caveats) for one round file; unwraps the driver
    wrapper and collects the reasons this round is untrustworthy."""
    with open(path) as f:
        doc = json.load(f)
    caveats: List[str] = []
    if isinstance(doc, dict) and "parsed" in doc and "cmd" in doc:
        if int(doc.get("rc", 0) or 0) != 0:
            caveats.append(f"rc={doc['rc']}")
        doc = doc.get("parsed") or {}
    if not isinstance(doc, dict):
        return {}, caveats + ["not a JSON object"]
    status = doc.get("status")
    if status in _BAD_STATUS:
        caveats.append(f"status={status}")
    if doc.get("error"):
        caveats.append(f"error: {str(doc['error'])[:120]}")
    if not doc:
        caveats.append("no parsed metrics")
    elif float(doc.get("vs_baseline") or 0.0) == 0.0 \
            and "vs_baseline" in doc:
        caveats.append("vs_baseline=0 (flagship did not run)")
    return doc, caveats


def classify(key: str) -> str:
    """'higher' | 'lower' | 'neutral' — which direction is better."""
    if _HIGHER.search(key):
        return "higher"
    if _LOWER.search(key):
        return "lower"
    return "neutral"


def diff_rounds(a: Dict, b: Dict, threshold: float) -> List[Dict]:
    """Per-key comparison rows for numeric keys present in both."""
    rows: List[Dict] = []
    for key in sorted(set(a) & set(b)):
        va, vb = a[key], b[key]
        if isinstance(va, bool) or isinstance(vb, bool):
            continue
        if not isinstance(va, (int, float)) \
                or not isinstance(vb, (int, float)):
            continue
        direction = classify(key)
        change = (vb - va) / abs(va) if va else None
        flag = ""
        if change is not None and direction != "neutral":
            worse = -change if direction == "higher" else change
            better = -worse
            if worse > threshold:
                flag = "REGRESSION"
            elif better > threshold:
                flag = "improved"
        rows.append({"key": key, "a": va, "b": vb, "change": change,
                     "direction": direction, "flag": flag})
    return rows


def _fmt_change(c: Optional[float]) -> str:
    return "n/a" if c is None else f"{c * 100:+.1f}%"


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.bench_diff",
        description="Diff two BENCH_*.json rounds, flag regressions")
    p.add_argument("round_a")
    p.add_argument("round_b")
    p.add_argument("--threshold", type=float, default=0.05,
                   help="relative change to flag (default 0.05 = 5%%)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    args = p.parse_args(argv)

    a, caveats_a = load_round(args.round_a)
    b, caveats_b = load_round(args.round_b)
    rows = diff_rounds(a, b, args.threshold)
    regressions = [r for r in rows if r["flag"] == "REGRESSION"]
    advisory = bool(caveats_a or caveats_b)

    doc = {
        "round_a": args.round_a, "round_b": args.round_b,
        "threshold": args.threshold,
        "caveats_a": caveats_a, "caveats_b": caveats_b,
        "advisory": advisory,
        "compared": len(rows),
        "regressions": [r["key"] for r in regressions],
        "rows": rows,
    }
    if args.as_json:
        print(json.dumps(doc, indent=1))
    else:
        print(f"bench_diff: {args.round_a} -> {args.round_b} "
              f"(threshold {args.threshold * 100:g}%)")
        for side, caveats in (("A", caveats_a), ("B", caveats_b)):
            for c in caveats:
                print(f"  caveat [{side}]: {c}")
        if not rows:
            print("  no comparable numeric keys")
        w = max((len(r["key"]) for r in rows), default=3)
        for r in rows:
            print(f"  {r['key']:<{w}}  {r['a']:>12}  ->  {r['b']:>12}  "
                  f"{_fmt_change(r['change']):>8}  {r['flag']}")
        if regressions:
            kind = "ADVISORY (broken round)" if advisory else "FAIL"
            print(f"  {len(regressions)} regression(s) past threshold "
                  f"— {kind}")
        else:
            print("  no regressions past threshold")
    return 1 if regressions and not advisory else 0


if __name__ == "__main__":  # pragma: no cover - CLI
    sys.exit(main())
