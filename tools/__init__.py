"""Repo tooling (API gate, op-registry compat, postmortem reader).

A real package so the CLIs are ``python -m``-invocable from the repo
root (``python -m tools.postmortem``, mirroring
``python -m paddle_tpu.observe.timeline``).
"""
