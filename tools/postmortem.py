"""Pretty-print a postmortem bundle (paddle_tpu.observe.health).

A bundle is what the stall watchdog / crash hook / bench failure path
leaves behind: ``meta.json``, ``stacks.txt``, ``trace.json``,
``metrics.prom``, ``flight.jsonl``, ``flags.json``, ``memory.json``,
``requests.json`` (per-request serving traces + SLO verdict — the
violator table renders here, full timelines via
``python -m tools.reqtrace``) in one ``bundle_<ts>_<pid>_<reason>``
directory.  This reader is pure stdlib —
it must work on a machine (or in a container) where the framework
itself won't even import, because that is exactly when you need it.

Usage::

    python -m tools.postmortem BUNDLE_DIR            # one bundle
    python -m tools.postmortem POSTMORTEM_DIR        # newest bundle in it
    python -m tools.postmortem BUNDLE --tail 50      # more flight events
    python -m tools.postmortem BUNDLE --stacks       # full thread stacks
    python -m tools.postmortem BUNDLE --metrics      # full metrics text
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

BUNDLE_FILES = ("meta.json", "stacks.txt", "trace.json", "metrics.prom",
                "flight.jsonl", "flags.json", "memory.json",
                "requests.json", "phases.json")


def _mb(nbytes) -> float:
    try:
        return round(int(nbytes) / 2 ** 20, 2)
    except (TypeError, ValueError):
        return 0.0


def _is_bundle(path: str) -> bool:
    return os.path.isfile(os.path.join(path, "meta.json"))


def resolve_bundle(path: str) -> str:
    """Accept a bundle dir directly, or a parent directory of bundles
    (pick the newest by mtime)."""
    path = os.path.abspath(path)
    if _is_bundle(path):
        return path
    if os.path.isdir(path):
        cands = [os.path.join(path, d) for d in os.listdir(path)
                 if d.startswith("bundle_")]
        cands = [c for c in cands if _is_bundle(c)]
        if cands:
            return max(cands, key=os.path.getmtime)
    raise FileNotFoundError(
        f"{path} is neither a postmortem bundle (no meta.json) nor a "
        f"directory containing bundle_* subdirectories")


def _read_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _read_text(path: str) -> Optional[str]:
    try:
        with open(path) as f:
            return f.read()
    except OSError:
        return None


def _fmt_event(ev: dict) -> str:
    rest = {k: v for k, v in ev.items()
            if k not in ("ts", "seq", "event")}
    body = " ".join(f"{k}={v!r}" for k, v in rest.items())
    return f"  [{ev.get('seq', '?'):>6}] {ev.get('event', '?'):<28} {body}"


def render(bundle: str, tail: int = 15, stacks: bool = False,
           metrics: bool = False, out=None) -> int:
    out = out or sys.stdout
    w = out.write
    meta = _read_json(os.path.join(bundle, "meta.json")) or {}
    w(f"postmortem bundle: {bundle}\n")
    w(f"  reason:   {meta.get('reason', '?')}\n")
    w(f"  time:     {meta.get('time', '?')}  pid {meta.get('pid', '?')}"
      f"  rank {meta.get('rank', '?')}/{meta.get('world_size', '?')}\n")
    prog = meta.get("progress") or {}
    if prog:
        w(f"  progress: dispatched={prog.get('dispatched')} "
          f"drained={prog.get('drained')} inflight={prog.get('inflight')} "
          f"oldest_inflight_age_s={prog.get('oldest_inflight_age_s')}\n")
    exc = meta.get("exception")
    if exc:
        w(f"  exception: {exc.get('type')}: {exc.get('value')}\n")
    extra = meta.get("extra")
    if extra:
        w(f"  extra:    {json.dumps(extra)[:500]}\n")

    # -- elastic supervisor restart history (fleet/elastic) ----------------
    hist = (extra or {}).get("restart_history")
    if hist:
        w(f"\nelastic restart history ({len(hist)} attempt(s)):\n")
        for h in hist:
            line = (f"  #{h.get('attempt', '?')}  "
                    f"world={h.get('world_size', '?')}  "
                    f"kind={h.get('kind', '?')}  "
                    f"step={h.get('step', '?')}  "
                    f"{str(h.get('error', ''))[:100]}")
            if h.get("dead_ranks"):
                line += f"  dead_ranks={h['dead_ranks']}"
            w(line + "\n")
    errs = meta.get("section_errors") or {}
    if errs:
        w(f"  section errors: {errs}\n")

    present = [f for f in BUNDLE_FILES
               if os.path.isfile(os.path.join(bundle, f))]
    w(f"  files:    {', '.join(present)}\n")

    # -- flight-recorder tail --------------------------------------------
    fl = _read_text(os.path.join(bundle, "flight.jsonl"))
    if fl is not None:
        events: List[dict] = []
        for line in fl.splitlines():
            try:
                events.append(json.loads(line))
            except ValueError:
                continue
        w(f"\nflight recorder ({len(events)} events, last {tail}):\n")
        for ev in events[-tail:]:
            w(_fmt_event(ev) + "\n")

    # -- threads ----------------------------------------------------------
    st = _read_text(os.path.join(bundle, "stacks.txt"))
    if st is not None:
        heads = [ln for ln in st.splitlines()
                 if ln.startswith("--- thread ")]
        w(f"\nthreads ({len(heads)}):\n")
        for h in heads:
            w(f"  {h.strip('- ')}\n")
        if stacks:
            w("\n" + st + "\n")

    # -- trace span count --------------------------------------------------
    tr = _read_json(os.path.join(bundle, "trace.json"))
    if tr is not None:
        evs = tr.get("traceEvents", [])
        spans = [e for e in evs if e.get("ph") == "X"]
        w(f"\ntracer: {len(spans)} spans "
          f"(dropped {tr.get('otherData', {}).get('dropped_spans', 0)}); "
          f"load trace.json in Perfetto/chrome://tracing\n")

    # -- XLA memory accounting (observe/xla_stats.py) ----------------------
    mem = _read_json(os.path.join(bundle, "memory.json"))
    if mem is not None:
        comps = mem.get("compiles") or []
        w(f"\nxla compiles recorded: {len(comps)}\n")
        if comps:
            c = comps[-1]
            w(f"  last: fingerprint {c.get('fingerprint', '?')}  "
              f"compile {c.get('compile_seconds', '?')}s  "
              f"executable {_mb(c.get('executable_size_bytes'))} MB\n")
            br = c.get("memory") or {}
            if br:
                w(f"  per-chip footprint: {_mb(br.get('total_bytes'))} MB "
                  f"(args {_mb(br.get('arguments_bytes'))}"
                  f" + outputs {_mb(br.get('outputs_bytes'))}"
                  f" + temps {_mb(br.get('temporaries_bytes'))}"
                  f" + code {_mb(br.get('generated_code_bytes'))}"
                  f" - aliased {_mb(br.get('aliased_bytes'))})\n")
            bud = c.get("budget") or {}
            if bud.get("verdict"):
                w(f"  budget gate: {bud['verdict']}")
                if "budget_bytes" in bud:
                    w(f"  (required {_mb(bud.get('required_bytes'))} MB"
                      f" vs budget {_mb(bud.get('budget_bytes'))} MB)")
                w("\n")
            rows = c.get("attribution") or []
            if rows:
                width = max(len(str(r.get("name", "?"))) for r in rows)
                w(f"  top vars ({len(rows)}):\n")
                w(f"    {'var':<{width}}  {'per-chip MB':>12}  "
                  f"{'global MB':>10}  {'kind':<5}  spec\n")
                for r in rows:
                    w(f"    {str(r.get('name', '?')):<{width}}  "
                      f"{_mb(r.get('per_chip_bytes')):>12}  "
                      f"{_mb(r.get('global_bytes')):>10}  "
                      f"{str(r.get('kind', '?')):<5}  "
                      f"{r.get('spec', '?')}\n")
        for d in (mem.get("device_memory") or []):
            w(f"  device {d.get('device', '?')}: "
              f"{_mb(d.get('bytes_in_use'))} MB in use of "
              f"{_mb(d.get('bytes_limit'))} MB\n")
        g = mem.get("hbm_gauges") or {}
        if any(g.values()):
            w(f"  hbm gauges (last heartbeat sample): "
              f"free {_mb(g.get('hbm_free_bytes'))} MB, "
              f"used {_mb(g.get('hbm_used_bytes'))} MB, "
              f"limit {_mb(g.get('hbm_limit_bytes'))} MB\n")

    # -- step-phase attribution (observe/phases.py) ------------------------
    ph = _read_json(os.path.join(bundle, "phases.json"))
    if ph is not None and ph.get("steps"):
        w(f"\nphase attribution ({ph['steps']} steps, "
          f"{ph.get('wall_s', 0)}s wall):\n")
        fr = ph.get("measured_fractions") or {}
        secs = ph.get("measured_s") or {}
        for b in ("compute", "comm_exposed", "host", "input_wait"):
            if b in fr:
                w(f"  {b:<12} {fr[b] * 100:>6.1f}%  "
                  f"({secs.get(b, 0)}s)\n")
        pred = (ph.get("predicted") or {}).get("predicted_fractions")
        if pred:
            w(f"  predicted:   compute {pred.get('compute', 0) * 100:.1f}% "
              f"/ exposed-comm {pred.get('comm_exposed', 0) * 100:.1f}%\n")
        total = ph.get("comm_exposed_s", 0) + ph.get("comm_hidden_s", 0)
        if total:
            w(f"  comm: {ph.get('comm_exposed_s')}s exposed / "
              f"{ph.get('comm_hidden_s')}s hidden "
              f"(share {ph.get('comm_exposed_share', 0) * 100:.1f}% "
              f"exposed)\n")
        rows = (ph.get("ledger") or [])[:8]
        if rows:
            width = max(len(str(r.get("id", "?"))) for r in rows)
            w(f"  top collectives ({len(ph.get('ledger') or [])}):\n")
            w(f"    {'id':<{width}}  {'MB/step':>8}  {'exposed s':>10}  "
              f"{'hidden s':>9}  overlap\n")
            for r in rows:
                w(f"    {str(r.get('id', '?')):<{width}}  "
                  f"{_mb(r.get('bytes_per_step')):>8}  "
                  f"{round(r.get('exposed_s', 0), 6):>10}  "
                  f"{round(r.get('hidden_s', 0), 6):>9}  "
                  f"{'yes' if r.get('overlap') else 'no'}\n")

    # -- per-request traces + SLO verdict (observe/request_trace + slo) ----
    rq = _read_json(os.path.join(bundle, "requests.json"))
    if rq is not None:
        w(f"\nrequests: {len(rq.get('retained') or [])} retained traces, "
          f"{len(rq.get('inflight') or [])} in flight at dump "
          f"(python -m tools.reqtrace "
          f"{os.path.join(bundle, 'requests.json')})\n")
        # the rendering lives once, in the sibling pure-stdlib reader
        try:
            from . import reqtrace as _reqtrace
        except ImportError:  # pragma: no cover - run as a bare script
            import reqtrace as _reqtrace
        _reqtrace.render_slo(rq.get("slo") or {}, out)
        viol = rq.get("violators") or []
        if viol:
            _reqtrace.render_table(viol, out, title="violators")

    # -- metrics -----------------------------------------------------------
    mt = _read_text(os.path.join(bundle, "metrics.prom"))
    if mt is not None:
        rows = [ln for ln in mt.splitlines()
                if ln and not ln.startswith("#")
                and "_bucket{" not in ln]
        w(f"\nmetrics snapshot ({len(rows)} series"
          f"{'' if metrics else ', --metrics for all'}):\n")
        keys = ("executor_steps_", "executor_inflight", "watchdog_",
                "postmortem_", "cluster_", "ckpt_saves", "ckpt_save_f",
                "health_", "hbm_", "executable_size", "mfu_flops",
                "compile_seconds_count", "executable_hlo_ops",
                "pass_layer_scan", "decode_", "ttft_", "tpot_",
                "spec_accept_rate", "prefill_chunks", "slo_burn_rate",
                "slo_budget_remaining", "goodput", "request_trace",
                "quant_", "pass_weight_quant", "elastic_", "chaos_",
                "overlap_", "pp_", "pipeline_scan",
                "collective_matmul", "pass_overlap_stretched",
                "emb_", "dlrm_", "flash_attn_", "prefill_pad",
                "pass_flash_attention", "phase_", "prof_",
                "comm_exposed", "comm_hidden", "migrate_", "disagg_",
                "autoscale_", "moe_", "ep_", "pass_ep")
        for ln in rows:
            if metrics or any(k in ln for k in keys):
                w(f"  {ln}\n")

    flg = _read_json(os.path.join(bundle, "flags.json"))
    if flg is not None:
        w(f"\nflags: {len(flg)} recorded "
          f"(stall_timeout_s={flg.get('stall_timeout_s')}, "
          f"max_inflight_steps={flg.get('max_inflight_steps')}); "
          f"full snapshot in flags.json\n")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.postmortem",
        description="Pretty-print a paddle_tpu postmortem bundle")
    ap.add_argument("bundle",
                    help="bundle directory, or a directory of bundle_* "
                         "subdirectories (newest wins)")
    ap.add_argument("--tail", type=int, default=15,
                    help="flight-recorder events to show (default 15)")
    ap.add_argument("--stacks", action="store_true",
                    help="print the full all-thread stack dump")
    ap.add_argument("--metrics", action="store_true",
                    help="print every metrics series, not just the "
                         "health-plane ones")
    args = ap.parse_args(argv)
    try:
        bundle = resolve_bundle(args.bundle)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    return render(bundle, tail=args.tail, stacks=args.stacks,
                  metrics=args.metrics)


if __name__ == "__main__":
    raise SystemExit(main())
