"""Op-registry compatibility checker (reference tools/check_op_desc.py +
framework/op_version_registry.h).

Dumps every registered lowering (name + grad availability) to OPS.spec;
--check fails when an op DISAPPEARS (removing an op breaks saved
programs — the compat contract; adding ops is always fine).

Usage:
    python tools/check_op_desc.py --update   # refresh OPS.spec
    python tools/check_op_desc.py --check    # gate: no op removed
"""
from __future__ import annotations

import argparse
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)
SPEC = os.path.join(ROOT, "OPS.spec")


def collect() -> list[str]:
    import paddle_tpu  # noqa: F401  (registers all lowerings)
    from paddle_tpu.framework.backward import GRAD_MAKERS
    from paddle_tpu.framework.lowering import LOWERINGS

    lines = []
    for name in sorted(LOWERINGS):
        grad = "explicit_grad" if name + "_grad" in LOWERINGS else (
            "grad_maker" if name in GRAD_MAKERS else "generic_vjp")
        lines.append(f"{name} {grad}")
    return lines


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--update", action="store_true")
    args = ap.parse_args(argv)
    lines = collect()
    text = "\n".join(lines) + "\n"
    if args.update:
        with open(SPEC, "w") as f:
            f.write(text)
        print(f"wrote {len(lines)} ops to {SPEC}")
        return 0
    if args.check:
        if not os.path.exists(SPEC):
            print("OPS.spec missing; run --update", file=sys.stderr)
            return 1
        with open(SPEC) as f:
            old = {ln.split()[0] for ln in f if ln.strip()}
        now = {ln.split()[0] for ln in lines}
        removed = sorted(old - now)
        if removed:
            print(f"ops REMOVED from the registry (breaks saved "
                  f"programs): {removed}", file=sys.stderr)
            return 1
        added = sorted(now - old)
        print(f"op registry ok: {len(now)} ops "
              f"({len(added)} new since OPS.spec)")
        return 0
    sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
