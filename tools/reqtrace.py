"""Pretty-print per-request serving traces (observe/request_trace.py).

Input is any of:

- a postmortem bundle's ``requests.json`` (or a bundle directory /
  ``postmortem`` parent — the newest bundle's section is used),
- a single-trace JSON file (``/debug/request/<id>`` saved to disk),
- a live ``/debug/request/<id>`` or ``/debug/requests`` URL.

Pure stdlib on purpose: like ``tools/postmortem.py`` it must work on a
machine where the framework itself won't import, because that is
exactly when you are reading a violator's timeline.

Usage::

    python -m tools.reqtrace requests.json            # SLO verdict + violator table
    python -m tools.reqtrace requests.json --id ID    # one trace's timeline
    python -m tools.reqtrace requests.json --all      # every violator timeline
    python -m tools.reqtrace http://HOST:PORT/debug/requests
    python -m tools.reqtrace http://HOST:PORT/debug/request/ID

A bundle's ``requests.json`` serializes FULL timelines for the
violators only; retained/in-flight rows carry header+summary (hit a
live ``/debug/request/<id>`` for a non-violator's events).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def _load(src: str):
    if src.startswith("http://") or src.startswith("https://"):
        from urllib.request import urlopen

        with urlopen(src, timeout=10) as r:
            return json.loads(r.read().decode())
    path = src
    if os.path.isdir(path):
        # a bundle dir (or a directory of bundles): use its
        # requests.json — newest bundle wins, same rule as
        # tools/postmortem.py
        cand = os.path.join(path, "requests.json")
        if not os.path.isfile(cand):
            bundles = [os.path.join(path, d) for d in os.listdir(path)
                       if d.startswith("bundle_")]
            bundles = [b for b in bundles
                       if os.path.isfile(os.path.join(b, "requests.json"))]
            if not bundles:
                raise FileNotFoundError(
                    f"{path} holds no requests.json (not a bundle?)")
            cand = os.path.join(max(bundles, key=os.path.getmtime),
                                "requests.json")
        path = cand
    with open(path) as f:
        return json.load(f)


def _ms(v) -> str:
    return "-" if v is None else f"{float(v):.1f}"


def render_trace(tr: dict, out=None) -> None:
    out = out or sys.stdout
    w = out.write
    s = tr.get("summary") or {}
    w(f"trace {tr.get('trace_id', '?')}  kind={tr.get('kind', '?')}  "
      f"replica={tr.get('replica', '?')}\n")
    w(f"  outcome:  {tr.get('outcome', 'in-flight')}"
      f"{'  (' + str(tr['reason']) + ')' if tr.get('reason') else ''}\n")
    viol = tr.get("violations") or []
    if viol:
        w(f"  SLO violations: {', '.join(viol)}\n")
    if s:
        parts = []
        for k, label, scale in (("latency_s", "latency", 1e3),
                                ("ttft_s", "ttft", 1e3),
                                ("tpot_s", "tpot", 1e3)):
            if s.get(k) is not None:
                parts.append(f"{label}={s[k] * scale:.1f}ms")
        if s.get("n_tokens") is not None:
            parts.append(f"tokens={s['n_tokens']}")
        if s.get("prompt_len") is not None:
            parts.append(f"prompt={s['prompt_len']}")
        if parts:
            w(f"  summary:  {'  '.join(parts)}\n")
    attrs = tr.get("attrs") or {}
    if attrs:
        w(f"  attrs:    "
          f"{' '.join(f'{k}={v}' for k, v in sorted(attrs.items()))}\n")
    evs = tr.get("events") or []
    if not evs and tr.get("n_events"):
        # retained/in-flight rows in a bundle's requests.json carry
        # header+summary only (violators serialize full timelines)
        w(f"  timeline:  {tr['n_events']} events recorded but not "
          f"serialized in this file — query a live "
          f"/debug/request/{tr.get('trace_id', '')} for them\n")
    if evs:
        dropped = ", %d dropped" % tr["dropped_events"] \
            if tr.get("dropped_events") else ""
        w(f"  timeline ({len(evs)} events{dropped}):\n")
        for ev in evs:
            rest = {k: v for k, v in ev.items()
                    if k not in ("t_ms", "name")}
            body = "  ".join(f"{k}={v}" for k, v in rest.items())
            w(f"    +{float(ev.get('t_ms', 0.0)):>10.3f}ms  "
              f"{ev.get('name', '?'):<18} {body}\n")


def render_table(rows: List[dict], out=None,
                 title: str = "requests") -> None:
    out = out or sys.stdout
    w = out.write
    w(f"{title} ({len(rows)}):\n")
    if not rows:
        return
    w(f"  {'trace_id':<18} {'replica':<10} {'phase/outcome':<14} "
      f"{'age/lat ms':>10} {'ttft ms':>8} {'tok':>4}  detail\n")
    for r in rows:
        s = r.get("summary") or {}
        phase = r.get("phase") or r.get("outcome") or "?"
        age = r.get("age_ms")
        if age is None:
            age = None if s.get("latency_s") is None \
                else s["latency_s"] * 1e3
        ttft = None if s.get("ttft_s") is None else s["ttft_s"] * 1e3
        tok = r.get("tokens", s.get("n_tokens", ""))
        detail = []
        if r.get("violations"):
            detail.append("SLO:" + ",".join(r["violations"]))
        if r.get("reason"):
            detail.append(str(r["reason"]))
        if r.get("slot") is not None:
            detail.append(f"slot={r['slot']}")
        if r.get("chunks_done"):
            detail.append(f"chunks={r['chunks_done']}")
        w(f"  {str(r.get('trace_id', '?')):<18} "
          f"{str(r.get('replica', '?')):<10} {str(phase):<14} "
          f"{_ms(age):>10} {_ms(ttft):>8} {str(tok):>4}  "
          f"{' '.join(detail)}\n")


def render_slo(slo: dict, out=None) -> None:
    out = out or sys.stdout
    w = out.write
    if not slo:
        return
    w(f"SLO verdict ({slo.get('observed', 0)} requests observed, "
      f"{slo.get('violations_total', 0)} violations, goodput "
      f"{slo.get('goodput_rps', 0.0):.3f} req/s):\n")
    burns = slo.get("burn_rates") or {}
    remaining = slo.get("budget_remaining") or {}
    for o in slo.get("objectives") or []:
        name = o.get("name", "?")
        rates = burns.get(name) or {}
        rate_s = "  ".join(f"{k}={v:.2f}x"
                           for k, v in sorted(rates.items()))
        thr = o.get("threshold_ms")
        w(f"  {name:<12} budget={o.get('budget')}"
          f"{'  thr=' + str(thr) + 'ms' if thr is not None else ''}  "
          f"burn[{rate_s}]  "
          f"budget_remaining={remaining.get(name, 1.0):.2%}\n")


def render(doc, trace_id: Optional[str] = None, show_all: bool = False,
           out=None) -> int:
    out = out or sys.stdout
    if isinstance(doc, dict) and "events" in doc \
            and "trace_id" in doc:  # one full trace
        render_trace(doc, out)
        return 0
    if isinstance(doc, dict) and "error" in doc and len(doc) == 1:
        out.write(f"{doc['error']}\n")
        return 1
    if isinstance(doc, dict) and "requests" in doc:  # /debug/requests
        render_table(doc.get("requests") or [], out,
                     title="in-flight requests")
        return 0
    # a postmortem requests.json section
    violators = doc.get("violators") or []
    retained = doc.get("retained") or []
    inflight = doc.get("inflight") or []
    if trace_id is not None:
        pool = {t.get("trace_id"): t
                for t in retained + inflight}
        pool.update({t.get("trace_id"): t for t in violators})
        tr = pool.get(trace_id)
        if tr is None:
            out.write(f"no trace {trace_id!r} in this file "
                      f"({len(pool)} known)\n")
            return 1
        render_trace(tr, out)
        return 0
    render_slo(doc.get("slo") or {}, out)
    render_table(violators, out, title="\nviolators (full timelines)")
    if show_all:
        for tr in violators:
            out.write("\n")
            render_trace(tr, out)
    out.write(f"\nretained traces: {len(retained)}   in-flight at dump: "
              f"{len(inflight)}   (--id <violator id> for its "
              f"timeline; non-violators carry summaries only)\n")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.reqtrace",
        description="Pretty-print paddle_tpu per-request serving traces")
    ap.add_argument("src",
                    help="requests.json / bundle dir / single-trace "
                         "JSON / /debug URL")
    ap.add_argument("--id", default=None,
                    help="render one trace's full timeline")
    ap.add_argument("--all", action="store_true",
                    help="render every violator's full timeline")
    args = ap.parse_args(argv)
    try:
        doc = _load(args.src)
    except (OSError, ValueError) as e:
        print(f"cannot load {args.src}: {e}", file=sys.stderr)
        return 2
    return render(doc, trace_id=args.id, show_all=args.all)


if __name__ == "__main__":
    raise SystemExit(main())
